// Shared-memory ring buffer: the native DataLoader transport.
//
// Parity target: the reference's shared-memory DataLoader path
// (use_shared_memory=True — workers place batch tensors in shm segments and
// pass descriptors through the C++ BlockingQueue, fluid/operators/reader/
// blocking_queue.h + core._convert_to_tensor_list shm machinery). Python
// multiprocessing.Queue pickles through a pipe — one extra copy and a
// syscall per message; this ring keeps payloads in one mmap'd segment with
// process-shared pthread synchronization, so a worker->main handoff is a
// single memcpy each side.
//
// Layout: [Header | data bytes]; records are [u32 len | payload] with
// wrap-around (a record never straddles the end: if the tail gap is too
// small, a 0xFFFFFFFF wrap marker is written and writing resumes at 0).
// Multi-producer/multi-consumer safe via the shared mutex.

#include <errno.h>
#include <fcntl.h>
#include <pthread.h>
#include <stdint.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <time.h>
#include <unistd.h>

#include <cstdlib>
#include <string>

namespace {

constexpr uint32_t kWrapMarker = 0xFFFFFFFFu;

struct Header {
  pthread_mutex_t mu;
  pthread_cond_t not_empty;
  pthread_cond_t not_full;
  uint64_t capacity;   // data area size
  uint64_t head;       // read offset
  uint64_t tail;       // write offset
  uint64_t used;       // bytes in flight (records + markers)
  uint32_t closed;
  uint32_t magic;
};

struct Ring {
  Header* h;
  uint8_t* data;
  size_t map_size;
  std::string name;
  bool owner;
};

timespec deadline_from_ms(int timeout_ms) {
  timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  ts.tv_sec += timeout_ms / 1000;
  ts.tv_nsec += (timeout_ms % 1000) * 1000000L;
  if (ts.tv_nsec >= 1000000000L) {
    ts.tv_sec += 1;
    ts.tv_nsec -= 1000000000L;
  }
  return ts;
}

}  // namespace

extern "C" {

void* pd_ring_create(const char* name, uint64_t capacity) {
  size_t map_size = sizeof(Header) + capacity;
  ::shm_unlink(name);  // stale segment from a crashed run
  int fd = ::shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  if (::ftruncate(fd, static_cast<off_t>(map_size)) != 0) {
    ::close(fd);
    ::shm_unlink(name);
    return nullptr;
  }
  void* mem = ::mmap(nullptr, map_size, PROT_READ | PROT_WRITE, MAP_SHARED,
                     fd, 0);
  ::close(fd);
  if (mem == MAP_FAILED) {
    ::shm_unlink(name);
    return nullptr;
  }
  auto* h = static_cast<Header*>(mem);
  pthread_mutexattr_t ma;
  pthread_mutexattr_init(&ma);
  pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
  // robust: if a worker dies holding the lock, the main process recovers
  // instead of deadlocking
  pthread_mutexattr_setrobust(&ma, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&h->mu, &ma);
  pthread_condattr_t ca;
  pthread_condattr_init(&ca);
  pthread_condattr_setpshared(&ca, PTHREAD_PROCESS_SHARED);
  pthread_cond_init(&h->not_empty, &ca);
  pthread_cond_init(&h->not_full, &ca);
  h->capacity = capacity;
  h->head = h->tail = h->used = 0;
  h->closed = 0;
  h->magic = 0x52494e47;  // "RING"
  auto* r = new Ring{h, static_cast<uint8_t*>(mem) + sizeof(Header),
                     map_size, name, true};
  return r;
}

void* pd_ring_attach(const char* name) {
  int fd = ::shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (::fstat(fd, &st) != 0) {
    ::close(fd);
    return nullptr;
  }
  void* mem = ::mmap(nullptr, static_cast<size_t>(st.st_size),
                     PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  ::close(fd);
  if (mem == MAP_FAILED) return nullptr;
  auto* h = static_cast<Header*>(mem);
  if (h->magic != 0x52494e47) {
    ::munmap(mem, static_cast<size_t>(st.st_size));
    return nullptr;
  }
  auto* r = new Ring{h, static_cast<uint8_t*>(mem) + sizeof(Header),
                     static_cast<size_t>(st.st_size), name, false};
  return r;
}

static int lock_robust(Header* h) {
  int rc = pthread_mutex_lock(&h->mu);
  if (rc == EOWNERDEAD) {
    pthread_mutex_consistent(&h->mu);
    rc = 0;
  }
  return rc;
}

// 0 ok, -1 timeout, -2 closed/error, -3 message larger than capacity
//
// Placement must be CONTIGUOUS-space aware, not just total-free aware: the
// free bytes are [tail, end)+[0, head) when tail >= head, or [tail, head)
// otherwise. A record goes either at tail (if the region there fits it) or
// wraps to offset 0 (only legal when the [0, head) region fits it) — never
// on top of unread data.
int pd_ring_put(void* rv, const uint8_t* buf, uint64_t len, int timeout_ms) {
  auto* r = static_cast<Ring*>(rv);
  Header* h = r->h;
  uint64_t need = 4 + len;
  if (need > h->capacity) return -3;
  timespec ts = deadline_from_ms(timeout_ms);
  if (lock_robust(h) != 0) return -2;
  for (;;) {
    if (h->closed) {
      pthread_mutex_unlock(&h->mu);
      return -2;
    }
    if (h->used == 0) h->head = h->tail = 0;  // empty: maximize contiguity
    uint64_t head = h->head, tail = h->tail;
    bool full = h->used > 0 && tail == head;
    uint64_t cont_tail = 0, cont_zero = 0;
    if (!full) {
      if (tail > head || h->used == 0) {
        cont_tail = h->capacity - tail;
        cont_zero = head;
      } else {  // tail < head
        cont_tail = head - tail;
      }
    }
    if (cont_tail >= need) {
      uint32_t len32 = static_cast<uint32_t>(len);
      memcpy(r->data + tail, &len32, 4);
      if (len) memcpy(r->data + tail + 4, buf, len);
      h->tail = (tail + need) % h->capacity;
      h->used += need;
      break;
    }
    if (cont_zero >= need) {  // wrap: mark the tail gap consumed
      if (cont_tail >= 4) memcpy(r->data + tail, &kWrapMarker, 4);
      h->used += cont_tail;
      uint32_t len32 = static_cast<uint32_t>(len);
      memcpy(r->data, &len32, 4);
      if (len) memcpy(r->data + 4, buf, len);
      h->tail = need % h->capacity;
      h->used += need;
      break;
    }
    int rc = timeout_ms < 0
                 ? pthread_cond_wait(&h->not_full, &h->mu)
                 : pthread_cond_timedwait(&h->not_full, &h->mu, &ts);
    if (rc == ETIMEDOUT) {
      pthread_mutex_unlock(&h->mu);
      return -1;
    }
  }
  pthread_cond_signal(&h->not_empty);
  pthread_mutex_unlock(&h->mu);
  return 0;
}

// 0 ok (out malloc'd), -1 timeout, -2 closed-and-empty/error
int pd_ring_get(void* rv, uint8_t** out, uint64_t* out_len, int timeout_ms) {
  auto* r = static_cast<Ring*>(rv);
  Header* h = r->h;
  timespec ts = deadline_from_ms(timeout_ms);
  if (lock_robust(h) != 0) return -2;
  for (;;) {
    while (h->used == 0) {
      if (h->closed) {
        pthread_mutex_unlock(&h->mu);
        return -2;
      }
      int rc = timeout_ms < 0
                   ? pthread_cond_wait(&h->not_empty, &h->mu)
                   : pthread_cond_timedwait(&h->not_empty, &h->mu, &ts);
      if (rc == ETIMEDOUT) {
        pthread_mutex_unlock(&h->mu);
        return -1;
      }
    }
    uint64_t head = h->head;
    uint64_t room_to_end = h->capacity - head;
    uint32_t len32;
    if (room_to_end < 4) {
      // unreachable gap smaller than a marker: skip to 0
      h->used -= room_to_end;
      h->head = 0;
      continue;
    }
    memcpy(&len32, r->data + head, 4);
    if (len32 == kWrapMarker) {
      h->used -= room_to_end;
      h->head = 0;
      continue;
    }
    uint8_t* buf = static_cast<uint8_t*>(std::malloc(len32 ? len32 : 1));
    memcpy(buf, r->data + head + 4, len32);
    h->head = (head + 4 + len32) % h->capacity;
    h->used -= 4 + len32;
    pthread_cond_signal(&h->not_full);
    pthread_mutex_unlock(&h->mu);
    *out = buf;
    *out_len = len32;
    return 0;
  }
}

int pd_ring_size(void* rv) {
  auto* r = static_cast<Ring*>(rv);
  if (lock_robust(r->h) != 0) return -1;
  int used = static_cast<int>(r->h->used);
  pthread_mutex_unlock(&r->h->mu);
  return used;
}

void pd_ring_close(void* rv) {
  auto* r = static_cast<Ring*>(rv);
  if (lock_robust(r->h) == 0) {
    r->h->closed = 1;
    pthread_cond_broadcast(&r->h->not_empty);
    pthread_cond_broadcast(&r->h->not_full);
    pthread_mutex_unlock(&r->h->mu);
  }
}

// Drop unlink responsibility (fork-inherited copies must not unlink the
// creator's segment when they finalize).
void pd_ring_set_owner(void* rv, int owner) {
  static_cast<Ring*>(rv)->owner = owner != 0;
}

void pd_ring_free(void* rv) {
  auto* r = static_cast<Ring*>(rv);
  bool owner = r->owner;
  std::string name = r->name;
  ::munmap(r->h, r->map_size);
  if (owner) ::shm_unlink(name.c_str());
  delete r;
}

void pd_ring_free_buf(uint8_t* p) { std::free(p); }

}  // extern "C"
