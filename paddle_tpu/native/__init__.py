"""Native (C++) runtime components.

The reference framework's control-plane runtime is C++ (TCPStore rendezvous,
allocators, executors — SURVEY.md §2.6/§2.9). The TPU build keeps the same
split: JAX/XLA/Pallas own the compute path, while host-side runtime services
live here as C++ shared libraries loaded through ctypes.

Libraries are compiled on demand with g++ into ``native/build/`` and cached;
a source-mtime check rebuilds after edits. No pybind11 — the C ABI plus
ctypes keeps the binding layer dependency-free.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_BUILD_DIR = os.path.join(_HERE, "build")
_lock = threading.Lock()
_cache: dict[str, ctypes.CDLL] = {}

_CXXFLAGS = ["-O2", "-std=c++17", "-fPIC", "-shared", "-pthread", "-Wall"]


def compile_shared_lib(sources, so: str, extra_flags=(), ldflags=(),
                       deps=(), verbose=False):
    """g++-compile ``sources`` into ``so`` if any source/dep is newer.

    Shared by the built-in native services and the custom-op extension
    builder (utils/cpp_extension). ``deps`` are additional freshness
    dependencies (included headers) that trigger a rebuild without being
    compiled; ``ldflags`` go AFTER the sources (GNU ld resolves -l
    libraries left-to-right). Concurrency-safe across processes: the tmp
    file is pid-suffixed and os.replace is atomic, so parallel builders
    each produce a complete .so and the last replace wins.
    """
    sources = [sources] if isinstance(sources, str) else list(sources)
    newest = max(os.path.getmtime(p) for p in [*sources, *deps])
    if os.path.exists(so) and os.path.getmtime(so) >= newest:
        return so
    tmp = so + f".tmp{os.getpid()}"
    cmd = ["g++", *_CXXFLAGS, *extra_flags, "-o", tmp, *sources, *ldflags]
    if verbose:
        print(" ".join(cmd))
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(
            f"native build failed: {' '.join(cmd)}\n{proc.stderr}")
    os.replace(tmp, so)  # atomic vs concurrent builders
    return so


def load_library(name: str) -> ctypes.CDLL:
    """Compile (if needed) and dlopen ``native/<name>.cc`` -> ``lib<name>.so``."""
    with _lock:
        if name in _cache:
            return _cache[name]
        src = os.path.join(_HERE, name + ".cc")
        if not os.path.exists(src):
            raise FileNotFoundError(src)
        os.makedirs(_BUILD_DIR, exist_ok=True)
        so = os.path.join(_BUILD_DIR, f"lib{name}.so")
        # glibc < 2.34 keeps shm_open/sem_* in librt; -shared links fine
        # without it but dlopen then fails with an undefined symbol unless
        # some other module happened to pull librt in first (import-order
        # flake). Explicit -lrt is a no-op stub on newer glibc.
        compile_shared_lib([src], so, ldflags=("-lrt",))
        lib = ctypes.CDLL(so)
        _cache[name] = lib
        return lib
