"""Native (C++) runtime components.

The reference framework's control-plane runtime is C++ (TCPStore rendezvous,
allocators, executors — SURVEY.md §2.6/§2.9). The TPU build keeps the same
split: JAX/XLA/Pallas own the compute path, while host-side runtime services
live here as C++ shared libraries loaded through ctypes.

Libraries are compiled on demand with g++ into ``native/build/`` and cached;
a source-mtime check rebuilds after edits. No pybind11 — the C ABI plus
ctypes keeps the binding layer dependency-free.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_BUILD_DIR = os.path.join(_HERE, "build")
_lock = threading.Lock()
_cache: dict[str, ctypes.CDLL] = {}

_CXXFLAGS = ["-O2", "-std=c++17", "-fPIC", "-shared", "-pthread", "-Wall"]


def load_library(name: str) -> ctypes.CDLL:
    """Compile (if needed) and dlopen ``native/<name>.cc`` -> ``lib<name>.so``."""
    with _lock:
        if name in _cache:
            return _cache[name]
        src = os.path.join(_HERE, name + ".cc")
        if not os.path.exists(src):
            raise FileNotFoundError(src)
        os.makedirs(_BUILD_DIR, exist_ok=True)
        so = os.path.join(_BUILD_DIR, f"lib{name}.so")
        if not os.path.exists(so) or os.path.getmtime(so) < os.path.getmtime(src):
            tmp = so + f".tmp{os.getpid()}"
            cmd = ["g++", *_CXXFLAGS, "-o", tmp, src]
            proc = subprocess.run(cmd, capture_output=True, text=True)
            if proc.returncode != 0:
                raise RuntimeError(
                    f"native build failed: {' '.join(cmd)}\n{proc.stderr}"
                )
            os.replace(tmp, so)  # atomic vs concurrent builders
        lib = ctypes.CDLL(so)
        _cache[name] = lib
        return lib
