// TCP key-value coordination store (native runtime component).
//
// Parity target: the reference's TCPStore rendezvous service
// (phi/core/distributed/store/tcp_store.h:121, tcp_utils.cc) — a
// set/get/add/wait KV store used to bootstrap distributed jobs. The TPU
// build uses it the same way: rank-0 hosts the server, every process
// (including rank-0) talks to it through a client socket to exchange
// coordinator addresses, barrier, and publish per-rank metadata before
// jax.distributed / mesh construction exists.
//
// Design: blocking threads, not an event loop. One acceptor thread plus one
// detached handler thread per client connection, all sharing a
// mutex-protected map with a condition variable for WAIT/GET blocking.
// This is a control-plane service (O(ranks) connections, O(keys) traffic),
// so per-connection threads are simpler and plenty fast.
//
// Wire protocol (little-endian, length-prefixed):
//   request:  u8 cmd | u32 keylen | key bytes | payload
//     cmd 0 SET:   payload = u32 vallen | val
//     cmd 1 GET:   payload = i32 timeout_ms   (blocks until key exists)
//     cmd 2 ADD:   payload = i64 delta        (creates key at 0 first)
//     cmd 3 WAIT:  payload = i32 timeout_ms
//     cmd 4 CHECK: no payload
//   response:
//     SET   -> u8 ok
//     GET   -> i32 status | u32 vallen | val   (status 0 ok, -1 timeout)
//     ADD   -> i64 new_value
//     WAIT  -> i32 status
//     CHECK -> u8 exists

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <set>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

bool send_all(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t w = ::send(fd, p, n, MSG_NOSIGNAL);
    if (w <= 0) {
      if (w < 0 && (errno == EINTR)) continue;
      return false;
    }
    p += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

bool recv_all(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) {
      if (r < 0 && errno == EINTR) continue;
      return false;
    }
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

class StoreServer {
 public:
  explicit StoreServer(int port) : port_(port) {}

  bool Start() {
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd_ < 0) return false;
    int one = 1;
    ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(static_cast<uint16_t>(port_));
    if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) <
        0) {
      ::close(listen_fd_);
      listen_fd_ = -1;
      return false;
    }
    if (port_ == 0) {
      socklen_t len = sizeof(addr);
      ::getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&addr), &len);
      port_ = ntohs(addr.sin_port);
    }
    if (::listen(listen_fd_, 128) < 0) {
      ::close(listen_fd_);
      listen_fd_ = -1;
      return false;
    }
    acceptor_ = std::thread([this] { AcceptLoop(); });
    return true;
  }

  void Stop() {
    stop_.store(true);
    if (listen_fd_ >= 0) ::shutdown(listen_fd_, SHUT_RDWR);
    if (acceptor_.joinable()) acceptor_.join();
    if (listen_fd_ >= 0) ::close(listen_fd_);
    listen_fd_ = -1;
    // Unblock every handler: shutdown their sockets (breaks recv_all) and wake
    // cv waiters, then join so no thread outlives this object.
    {
      std::lock_guard<std::mutex> lk(clients_mu_);
      for (int fd : client_fds_) ::shutdown(fd, SHUT_RDWR);
    }
    cv_.notify_all();
    std::vector<std::thread> to_join;
    {
      std::lock_guard<std::mutex> lk(clients_mu_);
      to_join.swap(handlers_);
    }
    for (auto& t : to_join)
      if (t.joinable()) t.join();
  }

  int port() const { return port_; }

  int ActiveClients() {
    std::lock_guard<std::mutex> lk(clients_mu_);
    return static_cast<int>(client_fds_.size());
  }

  ~StoreServer() { Stop(); }

 private:
  void AcceptLoop() {
    while (!stop_.load()) {
      sockaddr_in peer{};
      socklen_t len = sizeof(peer);
      int fd = ::accept(listen_fd_, reinterpret_cast<sockaddr*>(&peer), &len);
      if (fd < 0) {
        if (stop_.load()) break;
        if (errno == EINTR) continue;
        break;
      }
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      std::lock_guard<std::mutex> lk(clients_mu_);
      if (stop_.load()) {
        ::close(fd);
        break;
      }
      client_fds_.insert(fd);
      handlers_.emplace_back([this, fd] { HandleClient(fd); });
    }
  }

  void HandleClient(int fd) {
    while (!stop_.load()) {
      uint8_t cmd;
      uint32_t keylen;
      if (!recv_all(fd, &cmd, 1) || !recv_all(fd, &keylen, 4)) break;
      if (keylen > (1u << 20)) break;  // malformed
      std::string key(keylen, '\0');
      if (!recv_all(fd, key.data(), keylen)) break;
      bool ok = true;
      switch (cmd) {
        case 0: {  // SET
          uint32_t vallen;
          if (!recv_all(fd, &vallen, 4) || vallen > (1u << 30)) {
            ok = false;
            break;
          }
          std::string val(vallen, '\0');
          if (!recv_all(fd, val.data(), vallen)) {
            ok = false;
            break;
          }
          {
            std::lock_guard<std::mutex> lk(mu_);
            data_[key] = std::move(val);
          }
          cv_.notify_all();
          uint8_t resp = 1;
          ok = send_all(fd, &resp, 1);
          break;
        }
        case 1: {  // GET (blocking)
          int32_t timeout_ms;
          if (!recv_all(fd, &timeout_ms, 4)) {
            ok = false;
            break;
          }
          std::string val;
          int32_t status = WaitFor(key, timeout_ms, &val);
          uint32_t vallen = static_cast<uint32_t>(val.size());
          ok = send_all(fd, &status, 4) && send_all(fd, &vallen, 4) &&
               (vallen == 0 || send_all(fd, val.data(), vallen));
          break;
        }
        case 2: {  // ADD
          int64_t delta;
          if (!recv_all(fd, &delta, 8)) {
            ok = false;
            break;
          }
          int64_t result;
          {
            std::lock_guard<std::mutex> lk(mu_);
            int64_t cur = 0;
            auto it = data_.find(key);
            if (it != data_.end() && !it->second.empty()) {
              cur = std::strtoll(it->second.c_str(), nullptr, 10);
            }
            result = cur + delta;
            data_[key] = std::to_string(result);
          }
          cv_.notify_all();
          ok = send_all(fd, &result, 8);
          break;
        }
        case 3: {  // WAIT
          int32_t timeout_ms;
          if (!recv_all(fd, &timeout_ms, 4)) {
            ok = false;
            break;
          }
          int32_t status = WaitFor(key, timeout_ms, nullptr);
          ok = send_all(fd, &status, 4);
          break;
        }
        case 4: {  // CHECK
          uint8_t exists;
          {
            std::lock_guard<std::mutex> lk(mu_);
            exists = data_.count(key) ? 1 : 0;
          }
          ok = send_all(fd, &exists, 1);
          break;
        }
        default:
          ok = false;
      }
      if (!ok) break;
    }
    {
      std::lock_guard<std::mutex> lk(clients_mu_);
      client_fds_.erase(fd);
    }
    ::close(fd);
  }

  // Block until `key` exists (or timeout; <0 = infinite). Copies the value
  // out under the lock when `out` is non-null. Returns 0 ok, -1 timeout.
  int32_t WaitFor(const std::string& key, int32_t timeout_ms,
                  std::string* out) {
    std::unique_lock<std::mutex> lk(mu_);
    auto pred = [&] { return stop_.load() || data_.count(key) > 0; };
    if (timeout_ms < 0) {
      cv_.wait(lk, pred);
    } else if (!cv_.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                             pred)) {
      return -1;
    }
    if (!data_.count(key)) return -1;  // woken by stop
    if (out) *out = data_[key];
    return 0;
  }

  int port_;
  int listen_fd_ = -1;
  std::atomic<bool> stop_{false};
  std::thread acceptor_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::unordered_map<std::string, std::string> data_;
  std::mutex clients_mu_;
  std::set<int> client_fds_;
  std::vector<std::thread> handlers_;
};

class StoreClient {
 public:
  bool Connect(const char* host, int port, int timeout_ms) {
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::milliseconds(timeout_ms);
    addrinfo hints{};
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    addrinfo* res = nullptr;
    if (::getaddrinfo(host, std::to_string(port).c_str(), &hints, &res) != 0)
      return false;
    // Retry until deadline: the server rank may come up later than us.
    while (true) {
      for (addrinfo* ai = res; ai; ai = ai->ai_next) {
        int fd = ::socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
        if (fd < 0) continue;
        if (::connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) {
          int one = 1;
          ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
          fd_ = fd;
          ::freeaddrinfo(res);
          return true;
        }
        ::close(fd);
      }
      if (std::chrono::steady_clock::now() >= deadline) break;
      std::this_thread::sleep_for(std::chrono::milliseconds(50));
    }
    ::freeaddrinfo(res);
    return false;
  }

  ~StoreClient() {
    if (fd_ >= 0) ::close(fd_);
  }

  bool SendReq(uint8_t cmd, const std::string& key, const void* payload,
               size_t payload_len) {
    std::lock_guard<std::mutex> lk(mu_);
    uint32_t keylen = static_cast<uint32_t>(key.size());
    return send_all(fd_, &cmd, 1) && send_all(fd_, &keylen, 4) &&
           send_all(fd_, key.data(), keylen) &&
           (payload_len == 0 || send_all(fd_, payload, payload_len));
  }

  int fd() const { return fd_; }
  std::mutex mu_;  // serialize request/response pairs across threads

 private:
  int fd_ = -1;
};

}  // namespace

extern "C" {

void* pd_store_server_start(int port) {
  auto* s = new StoreServer(port);
  if (!s->Start()) {
    delete s;
    return nullptr;
  }
  return s;
}

int pd_store_server_port(void* h) {
  return static_cast<StoreServer*>(h)->port();
}

int pd_store_server_active_clients(void* h) {
  return static_cast<StoreServer*>(h)->ActiveClients();
}

void pd_store_server_stop(void* h) { delete static_cast<StoreServer*>(h); }

void* pd_store_client_new(const char* host, int port, int timeout_ms) {
  auto* c = new StoreClient();
  if (!c->Connect(host, port, timeout_ms)) {
    delete c;
    return nullptr;
  }
  return c;
}

void pd_store_client_free(void* h) { delete static_cast<StoreClient*>(h); }

int pd_store_set(void* h, const char* key, const uint8_t* val, int len) {
  auto* c = static_cast<StoreClient*>(h);
  std::string k(key);
  std::vector<char> payload(4 + len);
  uint32_t vallen = static_cast<uint32_t>(len);
  std::memcpy(payload.data(), &vallen, 4);
  if (len) std::memcpy(payload.data() + 4, val, len);
  std::unique_lock<std::mutex> lk(c->mu_);
  uint8_t cmd = 0;
  uint32_t keylen = static_cast<uint32_t>(k.size());
  if (!send_all(c->fd(), &cmd, 1) || !send_all(c->fd(), &keylen, 4) ||
      !send_all(c->fd(), k.data(), keylen) ||
      !send_all(c->fd(), payload.data(), payload.size()))
    return -1;
  uint8_t resp;
  return recv_all(c->fd(), &resp, 1) && resp == 1 ? 0 : -1;
}

// On success *out is malloc'd (caller frees with pd_store_free_buf).
int pd_store_get(void* h, const char* key, uint8_t** out, int* out_len,
                 int timeout_ms) {
  auto* c = static_cast<StoreClient*>(h);
  std::string k(key);
  std::unique_lock<std::mutex> lk(c->mu_);
  uint8_t cmd = 1;
  uint32_t keylen = static_cast<uint32_t>(k.size());
  int32_t tmo = timeout_ms;
  if (!send_all(c->fd(), &cmd, 1) || !send_all(c->fd(), &keylen, 4) ||
      !send_all(c->fd(), k.data(), keylen) || !send_all(c->fd(), &tmo, 4))
    return -2;
  int32_t status;
  uint32_t vallen;
  if (!recv_all(c->fd(), &status, 4) || !recv_all(c->fd(), &vallen, 4))
    return -2;
  if (vallen > 0) {
    uint8_t* buf = static_cast<uint8_t*>(std::malloc(vallen));
    if (!recv_all(c->fd(), buf, vallen)) {
      std::free(buf);
      return -2;
    }
    *out = buf;
  } else {
    *out = nullptr;
  }
  *out_len = static_cast<int>(vallen);
  return status;
}

long long pd_store_add(void* h, const char* key, long long delta) {
  auto* c = static_cast<StoreClient*>(h);
  std::string k(key);
  std::unique_lock<std::mutex> lk(c->mu_);
  uint8_t cmd = 2;
  uint32_t keylen = static_cast<uint32_t>(k.size());
  int64_t d = delta;
  if (!send_all(c->fd(), &cmd, 1) || !send_all(c->fd(), &keylen, 4) ||
      !send_all(c->fd(), k.data(), keylen) || !send_all(c->fd(), &d, 8))
    return INT64_MIN;
  int64_t result;
  if (!recv_all(c->fd(), &result, 8)) return INT64_MIN;
  return result;
}

int pd_store_wait(void* h, const char* key, int timeout_ms) {
  auto* c = static_cast<StoreClient*>(h);
  std::string k(key);
  std::unique_lock<std::mutex> lk(c->mu_);
  uint8_t cmd = 3;
  uint32_t keylen = static_cast<uint32_t>(k.size());
  int32_t tmo = timeout_ms;
  if (!send_all(c->fd(), &cmd, 1) || !send_all(c->fd(), &keylen, 4) ||
      !send_all(c->fd(), k.data(), keylen) || !send_all(c->fd(), &tmo, 4))
    return -2;
  int32_t status;
  if (!recv_all(c->fd(), &status, 4)) return -2;
  return status;
}

int pd_store_check(void* h, const char* key) {
  auto* c = static_cast<StoreClient*>(h);
  std::string k(key);
  std::unique_lock<std::mutex> lk(c->mu_);
  uint8_t cmd = 4;
  uint32_t keylen = static_cast<uint32_t>(k.size());
  if (!send_all(c->fd(), &cmd, 1) || !send_all(c->fd(), &keylen, 4) ||
      !send_all(c->fd(), k.data(), keylen))
    return -2;
  uint8_t exists;
  if (!recv_all(c->fd(), &exists, 1)) return -2;
  return exists;
}

void pd_store_free_buf(uint8_t* p) { std::free(p); }

}  // extern "C"
