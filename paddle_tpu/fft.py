"""paddle.fft parity over jnp.fft."""
from __future__ import annotations

import jax.numpy as jnp

from .autograd.engine import apply_op
from .framework.op_registry import register_op


def _wrap(op_name, fn):
    # NB: the public kwarg is ``name`` (paddle signature) — the op's
    # registry name must NOT be shadowed by it
    def op(x, n=None, axis=-1, norm="backward", name=None):
        return apply_op(op_name,
                        lambda v: fn(v, n=n, axis=axis, norm=norm), x)

    op.__name__ = op_name
    register_op(op_name)
    return op


def _wrap_nd(op_name, fn):
    def op(x, s=None, axes=None, norm="backward", name=None):
        return apply_op(op_name,
                        lambda v: fn(v, s=s, axes=axes, norm=norm), x)

    op.__name__ = op_name
    register_op(op_name)
    return op


fft = _wrap("fft", jnp.fft.fft)
ifft = _wrap("ifft", jnp.fft.ifft)
rfft = _wrap("rfft", jnp.fft.rfft)
irfft = _wrap("irfft", jnp.fft.irfft)
hfft = _wrap("hfft", jnp.fft.hfft)
ihfft = _wrap("ihfft", jnp.fft.ihfft)
fft2 = _wrap_nd("fft2", lambda v, s, axes, norm: jnp.fft.fft2(v, s=s, axes=axes or (-2, -1), norm=norm))
ifft2 = _wrap_nd("ifft2", lambda v, s, axes, norm: jnp.fft.ifft2(v, s=s, axes=axes or (-2, -1), norm=norm))
rfft2 = _wrap_nd("rfft2", lambda v, s, axes, norm: jnp.fft.rfft2(v, s=s, axes=axes or (-2, -1), norm=norm))
irfft2 = _wrap_nd("irfft2", lambda v, s, axes, norm: jnp.fft.irfft2(v, s=s, axes=axes or (-2, -1), norm=norm))
fftn = _wrap_nd("fftn", lambda v, s, axes, norm: jnp.fft.fftn(v, s=s, axes=axes, norm=norm))
ifftn = _wrap_nd("ifftn", lambda v, s, axes, norm: jnp.fft.ifftn(v, s=s, axes=axes, norm=norm))
rfftn = _wrap_nd("rfftn", lambda v, s, axes, norm: jnp.fft.rfftn(v, s=s, axes=axes, norm=norm))
irfftn = _wrap_nd("irfftn", lambda v, s, axes, norm: jnp.fft.irfftn(v, s=s, axes=axes, norm=norm))


def fftfreq(n, d=1.0, dtype=None, name=None):
    from .tensor.tensor import Tensor

    return Tensor(jnp.fft.fftfreq(n, d))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    from .tensor.tensor import Tensor

    return Tensor(jnp.fft.rfftfreq(n, d))


def fftshift(x, axes=None, name=None):
    return apply_op("fftshift", lambda v: jnp.fft.fftshift(v, axes=axes), x)


def ifftshift(x, axes=None, name=None):
    return apply_op("ifftshift", lambda v: jnp.fft.ifftshift(v, axes=axes), x)


def _swap_norm(norm):
    # hfft-family identities flip the transform direction, so the
    # normalization mode flips with it (numpy/torch convention)
    return {"backward": "forward", "forward": "backward"}.get(norm, norm)


def _hfftn_impl(v, s, axes, norm):
    # hfft identity: real output of a Hermitian input == irfftn of the
    # conjugate with the normalization direction flipped; jnp applies the
    # numpy/torch axes defaults (last len(s) dims when s is given)
    return jnp.fft.irfftn(jnp.conj(v), s=s, axes=axes, norm=_swap_norm(norm))


def _ihfftn_impl(v, s, axes, norm):
    return jnp.conj(jnp.fft.rfftn(v, s=s, axes=axes, norm=_swap_norm(norm)))


hfft2 = _wrap_nd("hfft2", lambda v, s, axes, norm: _hfftn_impl(
    v, s, axes or (-2, -1), norm))
ihfft2 = _wrap_nd("ihfft2", lambda v, s, axes, norm: _ihfftn_impl(
    v, s, axes or (-2, -1), norm))
hfftn = _wrap_nd("hfftn", _hfftn_impl)
ihfftn = _wrap_nd("ihfftn", _ihfftn_impl)
