"""paddle.sparse.nn parity: sparse layers + functional attention.

Reference: python/paddle/sparse/nn (Conv3D/SubmConv3D over phi sparse conv
kernels, BatchNorm, ReLU, MaxPool3D) and sparse attention
(phi/kernels/sparse/gpu/sparse_attention). TPU stance: sparse 3-D point
clouds compute as dense blocks (the MXU has no gather-matmul path worth
hand-rolling at this density regime); SubmConv3D preserves the input
pattern by sampling the dense result at the input's coordinates, which is
exactly the submanifold definition.
"""
from __future__ import annotations

import jax.numpy as jnp

from ... import nn as dense_nn
from ...autograd.engine import apply_op
from ...nn import functional as dense_F
from ...nn.layer.layers import Layer
from ...tensor.tensor import Tensor
from . import functional


class ReLU(Layer):
    def forward(self, x):
        from .. import relu

        return relu(x)


class Softmax(Layer):
    def __init__(self, axis=-1):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        from ..unary import softmax

        return softmax(x, self._axis)


class _SparseConvBase(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, subm=False,
                 bias_attr=None, data_format="NDHWC"):
        super().__init__()
        if data_format != "NDHWC":
            raise ValueError("sparse conv3d expects NDHWC (reference layout)")
        self._subm = subm
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._groups = groups
        self._dense = dense_nn.Conv3D(
            in_channels, out_channels, kernel_size, stride=stride,
            padding=padding, dilation=dilation, groups=groups,
            bias_attr=bias_attr, data_format="NDHWC")
        self.weight = self._dense.weight
        self.bias = self._dense.bias

    def forward(self, x):
        from .. import SparseCooTensor, to_sparse_coo

        dense_in = x.to_dense()
        out = self._dense(dense_in)
        if not self._subm:
            return to_sparse_coo(out, 4)  # N,D,H,W sparse; C dense
        # submanifold: output pattern == input pattern
        idx = x.indices_
        nz = tuple(idx._data[i] for i in range(4))

        def sample(dense):
            return dense[nz]

        vals = apply_op("subm_sample", sample, out)
        return SparseCooTensor(idx, vals, list(out.shape), coalesced=True)


class Conv3D(_SparseConvBase):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NDHWC"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, subm=False,
                         bias_attr=bias_attr, data_format=data_format)


class SubmConv3D(_SparseConvBase):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 key=None, weight_attr=None, bias_attr=None,
                 data_format="NDHWC"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, subm=True,
                         bias_attr=bias_attr, data_format=data_format)


class BatchNorm(Layer):
    """Sparse batch norm: normalizes over stored values per channel
    (reference: sparse/nn/layer/norm.py — statistics over nnz, not the
    implicit zeros)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NDHWC"):
        super().__init__()
        self._bn = dense_nn.BatchNorm1D(
            num_features, momentum=momentum, epsilon=epsilon,
            weight_attr=weight_attr, bias_attr=bias_attr)
        self.weight = self._bn.weight
        self.bias = self._bn.bias

    def forward(self, x):
        from .. import SparseCooTensor

        vals = self._bn(x.values())  # [nnz, C]
        return SparseCooTensor(x.indices_, vals, x.shape,
                               getattr(x, "_coalesced", False))


class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NDHWC"):
        super().__init__()
        self._k = kernel_size
        self._s = stride
        self._p = padding

    def forward(self, x):
        from .. import to_sparse_coo

        dense = x.to_dense()  # NDHWC
        nchw = dense.transpose([0, 4, 1, 2, 3])
        out = dense_F.max_pool3d(nchw, self._k, self._s, self._p)
        out = out.transpose([0, 2, 3, 4, 1])
        return to_sparse_coo(out, 4)


__all__ = ["ReLU", "Softmax", "Conv3D", "SubmConv3D", "BatchNorm",
           "MaxPool3D", "functional"]
