"""Sparse functional ops incl. attention.

Reference: paddle.sparse.nn.functional (relu/conv3d/subm_conv3d/attention —
phi/kernels/sparse/gpu/sparse_attention kernels). The attention here is the
CSR-masked variant: scores computed only where the mask stores entries,
row-softmax over stored entries, then SpMM against V.
"""
from __future__ import annotations

import jax.numpy as jnp

from ...autograd.engine import apply_op
from ...tensor.tensor import Tensor


def relu(x):
    from .. import relu as _relu

    return _relu(x)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
           groups=1, data_format="NDHWC"):
    from ...nn import functional as dense_F
    from .. import to_sparse_coo

    dense = x.to_dense().transpose([0, 4, 1, 2, 3])
    out = dense_F.conv3d(dense, weight, bias, stride, padding, dilation,
                         groups)
    return to_sparse_coo(out.transpose([0, 2, 3, 4, 1]), 4)


def attention(query: Tensor, key: Tensor, value: Tensor, sparse_mask,
              key_padding_mask=None, attn_mask=None):
    """Sparse-mask attention: Q,K,V are [B, H, L, D] dense; sparse_mask is a
    [B*H, L, L]-patterned CSR batch (reference sparse attention contract:
    one CSR per batch*head with identical pattern allowed). Returns dense
    [B, H, L, D]."""
    import numpy as np

    B, H, L, D = (int(s) for s in query.shape)
    rows = jnp.asarray(sparse_mask._row_indices())  # over flattened [B*H*L]
    cols = sparse_mask.cols_._data
    # rows index into B*H*L row space; recover (bh, l)
    bh = rows // L
    qrow = rows % L
    scale = 1.0 / float(np.sqrt(D))
    n_rows = B * H * L

    def fn(q, k, v, kpm, am):
        import jax

        qf = q.reshape(B * H, L, D)
        kf = k.reshape(B * H, L, D)
        vf = v.reshape(B * H, L, D)
        # sampled scores at stored (row, col) positions
        scores = (qf[bh, qrow] * kf[bh, cols]).sum(-1) * scale
        b_idx = bh // H  # batch of each stored entry
        # reference contract: both masks are 0/1, 0 = masked out
        if kpm is not None:  # key_padding_mask [B, L]
            scores = jnp.where(kpm[b_idx, cols] != 0, scores, -1e9)
        if am is not None:  # attn_mask [L, L]
            scores = jnp.where(am[qrow, cols] != 0, scores, -1e9)
        row_max = jax.ops.segment_max(scores, rows, num_segments=n_rows)
        p = jnp.exp(scores - row_max[rows])
        denom = jax.ops.segment_sum(p, rows, num_segments=n_rows)
        p = p / jnp.maximum(denom[rows], 1e-20)
        out = jax.ops.segment_sum(p[:, None] * vf[bh, cols], rows,
                                  num_segments=n_rows)
        return out.reshape(B, H, L, D)

    return apply_op("sparse_attention", fn, query, key, value,
                    key_padding_mask, attn_mask)
