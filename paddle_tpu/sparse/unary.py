"""Sparse unary ops: dense math on the values array, pattern unchanged.

Reference: paddle/phi/kernels/sparse/unary_kernel.h — the op set is exactly
the zero-preserving functions (f(0)=0), so applying f to values alone is
the whole kernel. Gradients flow through the values Tensor via the engine.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..autograd.engine import apply_op


def _unary(name, fn):
    def op(x):
        vals = apply_op(name, fn, x.values())
        if x.is_sparse_coo:
            from . import SparseCooTensor

            return SparseCooTensor(x.indices_, vals, x.shape, x._coalesced)
        from . import SparseCsrTensor

        return SparseCsrTensor(x.crows_, x.cols_, vals, x.shape)

    op.__name__ = f"sparse_{name}"
    return op


relu = _unary("relu", lambda v: jnp.maximum(v, 0))
relu6 = _unary("relu6", lambda v: jnp.clip(v, 0, 6))
tanh = _unary("tanh", jnp.tanh)
sin = _unary("sin", jnp.sin)
sinh = _unary("sinh", jnp.sinh)
tan = _unary("tan", jnp.tan)
asin = _unary("asin", jnp.arcsin)
asinh = _unary("asinh", jnp.arcsinh)
atan = _unary("atan", jnp.arctan)
atanh = _unary("atanh", jnp.arctanh)
sqrt = _unary("sqrt", jnp.sqrt)
square = _unary("square", jnp.square)
abs = _unary("abs", jnp.abs)
neg = _unary("neg", jnp.negative)
log1p = _unary("log1p", jnp.log1p)
expm1 = _unary("expm1", jnp.expm1)
deg2rad = _unary("deg2rad", jnp.deg2rad)
rad2deg = _unary("rad2deg", jnp.rad2deg)


def pow(x, factor):
    return _unary("pow", lambda v: jnp.power(v, factor))(x)


def cast(x, index_dtype=None, value_dtype=None):
    out = x.astype(value_dtype) if value_dtype is not None else x
    return out


def softmax(x, axis=-1):
    """Sparse softmax over the last axis of a 2-D CSR matrix: softmax within
    each row's stored entries (reference:
    phi/kernels/sparse/softmax_kernel.h — zeros stay zero; probability mass
    is distributed over stored positions only)."""
    if not x.is_sparse_csr:
        raise ValueError("sparse softmax expects a SparseCsrTensor")
    if axis not in (-1, len(x.shape) - 1):
        raise ValueError("sparse softmax supports the last axis only")
    import jax
    import numpy as np

    rows = jnp.asarray(x._row_indices())
    n_rows = x.shape[0]

    def fn(vals):
        row_max = jax.ops.segment_max(vals, rows, num_segments=n_rows)
        shifted = jnp.exp(vals - row_max[rows])
        denom = jax.ops.segment_sum(shifted, rows, num_segments=n_rows)
        return shifted / denom[rows]

    vals = apply_op("sparse_softmax", fn, x.values())
    from . import SparseCsrTensor

    return SparseCsrTensor(x.crows_, x.cols_, vals, x.shape)
