"""Sparse binary + matmul ops.

Reference: phi/kernels/sparse/elementwise_kernel.h (same-pattern fast path,
union-pattern general path) and matmul_kernel.h (spmm / sddmm a.k.a.
masked_matmul). On TPU the matmuls canonicalize to dense MXU matmuls with
gather/scatter at the edges — XLA fuses the scatter into the epilogue.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..autograd.engine import apply_op
from ..tensor.tensor import Tensor


def _same_pattern(x, y) -> bool:
    if x.is_sparse_coo and y.is_sparse_coo:
        return x.indices_.shape == y.indices_.shape and bool(
            np.array_equal(np.asarray(x.indices_._data),
                           np.asarray(y.indices_._data)))
    if x.is_sparse_csr and y.is_sparse_csr:
        return bool(
            np.array_equal(np.asarray(x.crows_._data), np.asarray(y.crows_._data))
            and np.array_equal(np.asarray(x.cols_._data), np.asarray(y.cols_._data)))
    return False


def _ewise(name, fn, x, y):
    from . import SparseCooTensor, to_sparse_coo

    if _same_pattern(x, y):
        vals = apply_op(f"sparse_{name}", fn, x.values(), y.values())
        if x.is_sparse_coo:
            return SparseCooTensor(x.indices_, vals, x.shape,
                                   getattr(x, "_coalesced", False))
        from . import SparseCsrTensor

        return SparseCsrTensor(x.crows_, x.cols_, vals, x.shape)
    # union pattern: go through dense (gradient-correct; XLA fuses)
    dense = apply_op(f"sparse_{name}_dense", fn, x.to_dense(), y.to_dense())
    out = to_sparse_coo(dense, len(x.shape))
    return out if x.is_sparse_coo else out.to_sparse_csr()


def add(x, y):
    return _ewise("add", jnp.add, x, y)


def subtract(x, y):
    return _ewise("subtract", jnp.subtract, x, y)


def multiply(x, y):
    if isinstance(y, (int, float)):
        from .unary import _unary

        return _unary("scale", lambda v: v * y)(x)
    return _ewise("multiply", jnp.multiply, x, y)


def divide(x, y):
    if isinstance(y, (int, float)):
        from .unary import _unary

        return _unary("scale_div", lambda v: v / y)(x)
    if _same_pattern(x, y):
        return _ewise("divide", jnp.divide, x, y)
    # differing patterns: restrict to x's pattern — 0/y = 0 stays implicit,
    # x/0 at an x-stored site is a genuine inf; a dense/dense fallback would
    # instead store inf/nan at EVERY unstored site (nnz ~ numel blowup)
    from . import SparseCooTensor

    coo = x if x.is_sparse_coo else x.to_sparse_coo()
    sd = coo.sparse_dim()
    nz = tuple(coo.indices_._data[i] for i in range(sd))

    def fn(vals, ydense):
        return vals / ydense[nz]

    vals = apply_op("sparse_divide_sampled", fn, coo.values(), y.to_dense())
    out = SparseCooTensor(coo.indices_, vals, coo.shape,
                          getattr(coo, "_coalesced", False))
    return out if x.is_sparse_coo else out.to_sparse_csr()


def matmul(x, y: Tensor) -> Tensor:
    """sparse @ dense -> dense (SpMM). COO path: gather-scatter matmul so
    only stored entries contribute; values gradient flows through vjp."""
    if getattr(x, "is_sparse_csr", False):
        x = x.to_sparse_coo()
    if getattr(x, "is_sparse_coo", False):
        if x.sparse_dim() != 2 or x.dense_dim() != 0:
            raise ValueError("sparse matmul expects a 2-D sparse matrix")
        n_rows = x.shape[0]
        rows = x.indices_._data[0]
        cols = x.indices_._data[1]

        def fn(vals, dense):
            import jax

            gathered = dense[cols] * vals[:, None]  # [nnz, N]
            return jax.ops.segment_sum(gathered, rows, num_segments=n_rows)

        return apply_op("sparse_matmul", fn, x.values(), y)
    raise ValueError("matmul expects a sparse lhs")


def masked_matmul(x: Tensor, y: Tensor, mask):
    """SDDMM: (x @ y) sampled at mask's sparsity pattern -> sparse with
    mask's pattern (reference: phi sparse masked_matmul)."""
    from . import SparseCsrTensor

    if not getattr(mask, "is_sparse_csr", False):
        raise ValueError("masked_matmul mask must be SparseCsrTensor")
    rows = jnp.asarray(mask._row_indices())
    cols = mask.cols_._data

    def fn(a, b):
        # only compute the sampled dot products: [nnz, K] x [nnz, K] -> [nnz]
        return (a[rows] * b[:, cols].T).sum(-1)

    vals = apply_op("sparse_sddmm", fn, x, y)
    return SparseCsrTensor(mask.crows_, mask.cols_, vals, mask.shape)
