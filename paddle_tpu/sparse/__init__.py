"""paddle.sparse parity package (SURVEY.md §2.8: COO/CSR tensor API +
sparse nn backed by phi/kernels/sparse).

TPU-native design: a sparse tensor is (index arrays + a dense values
Tensor). The values Tensor is an ordinary autograd Tensor, so every sparse
op that is "dense math on values" (unary ops, add of same-pattern tensors,
scaling) differentiates through the existing engine for free; ops that
change sparsity pattern (to_dense, matmul against dense) lower to XLA
scatter/gather + matmul — on TPU the MXU wants dense tiles, so compute
canonicalizes to dense blocks instead of the reference's per-backend sparse
CUDA kernels (phi/kernels/sparse/). The structural arrays (indices/crows/
cols) are non-differentiable by construction.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..autograd.engine import apply_op
from ..tensor.tensor import Tensor
from . import nn
from .binary import add, divide, masked_matmul, matmul, multiply, subtract
from .unary import (
    asin,
    asinh,
    atan,
    atanh,
    abs,
    cast,
    deg2rad,
    expm1,
    log1p,
    neg,
    pow,
    rad2deg,
    relu,
    relu6,
    sin,
    sinh,
    softmax,
    sqrt,
    square,
    tan,
    tanh,
)


def _as_tensor(x, dtype=None):
    if isinstance(x, Tensor):
        return x if dtype is None else Tensor(x._data.astype(dtype))
    return Tensor(jnp.asarray(x, dtype))


class SparseCooTensor:
    """COO sparse tensor: ``indices`` [sparse_dim, nnz] int64, ``values``
    [nnz, *dense_dims] (reference: phi/core/sparse_coo_tensor.h)."""

    is_sparse_coo = True
    is_sparse_csr = False

    def __init__(self, indices: Tensor, values: Tensor, shape, coalesced=False):
        self.indices_ = _as_tensor(indices, jnp.int64)
        self.values_ = _as_tensor(values)
        self.shape = list(int(d) for d in shape)
        self._coalesced = coalesced

    # -- accessors (paddle Tensor method parity) --
    def indices(self) -> Tensor:
        return self.indices_

    def values(self) -> Tensor:
        return self.values_

    def nnz(self) -> int:
        return int(self.indices_.shape[1])

    @property
    def dtype(self):
        return self.values_.dtype

    @property
    def stop_gradient(self):
        return self.values_.stop_gradient

    @stop_gradient.setter
    def stop_gradient(self, v):
        self.values_.stop_gradient = v

    @property
    def grad(self):
        return self.values_.grad

    def backward(self, *a, **k):
        return self.values_.backward(*a, **k)

    def sparse_dim(self) -> int:
        return int(self.indices_.shape[0])

    def dense_dim(self) -> int:
        return len(self.shape) - self.sparse_dim()

    def to_dense(self) -> Tensor:
        sd = self.sparse_dim()
        shape = tuple(self.shape)

        def fn(idx, vals):
            out = jnp.zeros(shape, vals.dtype)
            return out.at[tuple(idx[i] for i in range(sd))].add(vals)

        return apply_op("sparse_to_dense", fn, self.indices_, self.values_)

    def to_sparse_csr(self) -> "SparseCsrTensor":
        if self.sparse_dim() != 2 or self.dense_dim() != 0:
            raise ValueError("to_sparse_csr supports 2-D COO only")
        coo = self.coalesce()
        rows = np.asarray(coo.indices_._data[0])
        n_rows = self.shape[0]
        crows = np.zeros(n_rows + 1, np.int64)
        np.add.at(crows, rows + 1, 1)
        crows = np.cumsum(crows)
        return SparseCsrTensor(
            Tensor(jnp.asarray(crows)), Tensor(coo.indices_._data[1]),
            coo.values_, self.shape)

    def coalesce(self) -> "SparseCooTensor":
        """Sum duplicate coordinates (reference: sparse coalesce kernel).
        Runs on host for the index bookkeeping; values reduction is an XLA
        segment-sum so gradients flow."""
        if self._coalesced:
            return self
        idx = np.asarray(self.indices_._data)
        flat = np.ravel_multi_index(
            idx, tuple(self.shape[: self.sparse_dim()]))
        uniq, inverse = np.unique(flat, return_inverse=True)
        new_idx = np.stack(np.unravel_index(
            uniq, tuple(self.shape[: self.sparse_dim()])))
        num = len(uniq)
        inv = jnp.asarray(inverse)

        def fn(vals):
            return jax.ops.segment_sum(vals, inv, num_segments=num)

        new_vals = apply_op("sparse_coalesce", fn, self.values_)
        return SparseCooTensor(Tensor(jnp.asarray(new_idx)), new_vals,
                               self.shape, coalesced=True)

    def is_coalesced(self) -> bool:
        return self._coalesced

    def astype(self, dtype):
        return SparseCooTensor(self.indices_, self.values_.astype(dtype),
                               self.shape, self._coalesced)

    cast = astype

    def transpose(self, perm):
        if self.dense_dim() != 0:
            raise ValueError("transpose supports pure sparse dims only")
        new_idx = self.indices_._data[jnp.asarray(perm)]
        return SparseCooTensor(
            Tensor(new_idx), self.values_,
            [self.shape[p] for p in perm])

    def __add__(self, other):
        return add(self, other)

    def __sub__(self, other):
        return subtract(self, other)

    def __mul__(self, other):
        return multiply(self, other)

    def __truediv__(self, other):
        return divide(self, other)

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")


class SparseCsrTensor:
    """CSR sparse matrix: crows [rows+1], cols [nnz], values [nnz]
    (reference: phi/core/sparse_csr_tensor.h)."""

    is_sparse_coo = False
    is_sparse_csr = True

    def __init__(self, crows: Tensor, cols: Tensor, values: Tensor, shape):
        self.crows_ = _as_tensor(crows, jnp.int64)
        self.cols_ = _as_tensor(cols, jnp.int64)
        self.values_ = _as_tensor(values)
        self.shape = list(int(d) for d in shape)

    def crows(self) -> Tensor:
        return self.crows_

    def cols(self) -> Tensor:
        return self.cols_

    def values(self) -> Tensor:
        return self.values_

    def nnz(self) -> int:
        return int(self.cols_.shape[0])

    @property
    def dtype(self):
        return self.values_.dtype

    @property
    def stop_gradient(self):
        return self.values_.stop_gradient

    @stop_gradient.setter
    def stop_gradient(self, v):
        self.values_.stop_gradient = v

    @property
    def grad(self):
        return self.values_.grad

    def _row_indices(self):
        crows = np.asarray(self.crows_._data)
        return np.repeat(np.arange(len(crows) - 1), np.diff(crows))

    def to_sparse_coo(self, sparse_dim: int = 2) -> SparseCooTensor:
        rows = jnp.asarray(self._row_indices())
        idx = jnp.stack([rows, self.cols_._data])
        return SparseCooTensor(Tensor(idx), self.values_, self.shape,
                               coalesced=True)

    def to_dense(self) -> Tensor:
        return self.to_sparse_coo().to_dense()

    def astype(self, dtype):
        return SparseCsrTensor(self.crows_, self.cols_,
                               self.values_.astype(dtype), self.shape)

    cast = astype

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")


# ---------------------------------------------------------------------------
# creation API (reference: python/paddle/sparse/creation.py)
# ---------------------------------------------------------------------------

def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      place=None, stop_gradient=True) -> SparseCooTensor:
    idx = _as_tensor(indices, jnp.int64)
    vals = _as_tensor(values, dtype)
    if shape is None:
        maxes = np.asarray(idx._data).max(axis=1) + 1
        shape = list(maxes) + list(vals.shape[1:])
    vals.stop_gradient = stop_gradient
    return SparseCooTensor(idx, vals, shape)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      place=None, stop_gradient=True) -> SparseCsrTensor:
    vals = _as_tensor(values, dtype)
    vals.stop_gradient = stop_gradient
    return SparseCsrTensor(_as_tensor(crows, jnp.int64),
                           _as_tensor(cols, jnp.int64), vals, shape)


def to_sparse_coo(x: Tensor, sparse_dim: int) -> SparseCooTensor:
    """Dense -> COO over the leading sparse_dim dims (paddle
    Tensor.to_sparse_coo)."""
    arr = np.asarray(x._data)
    reduced = arr
    if arr.ndim > sparse_dim:
        reduced = np.abs(arr).sum(axis=tuple(range(sparse_dim, arr.ndim)))
    nz = np.nonzero(reduced)
    idx = np.stack(nz)

    def fn(dense):
        return dense[tuple(jnp.asarray(i) for i in nz)]

    vals = apply_op("dense_to_sparse", fn, x)
    return SparseCooTensor(Tensor(jnp.asarray(idx)), vals, x.shape,
                           coalesced=True)


def to_sparse_csr(x: Tensor) -> SparseCsrTensor:
    return to_sparse_coo(x, 2).to_sparse_csr()


is_sparse = lambda x: getattr(x, "is_sparse_coo", False) or getattr(
    x, "is_sparse_csr", False)

__all__ = [
    "SparseCooTensor", "SparseCsrTensor", "sparse_coo_tensor",
    "sparse_csr_tensor", "to_sparse_coo", "to_sparse_csr", "is_sparse",
    "nn", "add", "subtract", "multiply", "divide", "matmul",
    "masked_matmul", "relu", "relu6", "tanh", "sin", "sinh", "tan", "sqrt",
    "square", "abs", "pow", "neg", "log1p", "expm1", "deg2rad", "rad2deg",
    "cast", "softmax",
]


# --- round-5 module-level tail (reference python/paddle/sparse/__init__.py:
# transpose/sum/reshape/slice/coalesce/is_same_shape/mv/addmm/pca_lowrank/
# isnan) ---------------------------------------------------------------------
from .unary import _unary as _sparse_unary

isnan = _sparse_unary("isnan", jnp.isnan)


def transpose(x, perm, name=None):
    """Permute sparse dims (reference sparse/unary.py transpose)."""
    return x.transpose(perm)


def coalesce(x, name=None):
    """Merge duplicate COO indices (reference sparse/unary.py coalesce)."""
    return x.coalesce()


def is_same_shape(x, y) -> bool:
    return list(x.shape) == list(y.shape)


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    """Sum of a sparse tensor's stored values along ``axis`` (reference
    sparse/unary.py sum). axis=None returns a dense 0-D total; otherwise the
    result is computed on the dense equivalent and re-sparsified, which on
    XLA is the same segment-reduce the reference's kernel performs."""
    import builtins

    from ..tensor import math as _math

    if axis is None:
        total = _math.sum(x.values())
        return total.astype(dtype) if dtype is not None else total
    dense = x.to_dense()
    out = _math.sum(dense, axis=axis, keepdim=keepdim)
    if dtype is not None:
        out = out.astype(dtype)
    if x.is_sparse_coo:
        return to_sparse_coo(out, builtins.max(out._data.ndim, 1))
    return out


def reshape(x, shape, name=None):
    """Reshape a sparse COO tensor by recomputing linear indices host-side
    (reference sparse/unary.py reshape)."""
    import numpy as _np

    old_shape = list(x.shape)
    shape = list(shape)
    n = int(_np.prod(old_shape))
    if -1 in shape:
        known = int(_np.prod([s for s in shape if s != -1]))
        shape[shape.index(-1)] = n // known
    idx = _np.asarray(x.indices().numpy())
    linear = _np.ravel_multi_index(tuple(idx), tuple(old_shape))
    new_idx = _np.stack(_np.unravel_index(linear, tuple(shape)))
    return SparseCooTensor(Tensor(jnp.asarray(new_idx)), x.values(), shape,
                           coalesced=False)


def slice(x, axes, starts, ends, name=None):
    """Slice a sparse COO tensor along ``axes`` (reference sparse slice):
    keep stored entries inside the window, shift their indices."""
    import numpy as _np

    idx = _np.asarray(x.indices().numpy())
    shape = list(x.shape)
    keep = _np.ones(idx.shape[1], bool)
    new_shape = list(shape)
    offsets = _np.zeros(len(shape), _np.int64)
    for a, st, en in zip(axes, starts, ends):
        dim = shape[a]
        st = st + dim if st < 0 else builtins_min(st, dim)
        en = en + dim if en < 0 else builtins_min(en, dim)
        keep &= (idx[a] >= st) & (idx[a] < en)
        offsets[a] = st
        new_shape[a] = en - st
    sel = _np.nonzero(keep)[0]
    new_idx = idx[:, sel] - offsets[:, None]
    from ..tensor.manipulation import gather as _gather

    vals = _gather(x.values(), Tensor(jnp.asarray(sel)), axis=0)
    return SparseCooTensor(Tensor(jnp.asarray(new_idx)), vals, new_shape,
                           coalesced=False)


def builtins_min(a, b):
    return a if a < b else b


def mv(x, vec, name=None):
    """Sparse matrix x dense vector (reference sparse/binary.py mv)."""
    from ..tensor.manipulation import reshape as _reshape

    return _reshape(matmul(x, _reshape(vec, [-1, 1])), [-1])


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """beta * input + alpha * (x @ y) with sparse ``x`` (reference
    sparse/binary.py addmm)."""
    return input * beta + matmul(x, y) * alpha


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """PCA of a sparse matrix via its dense equivalent (reference
    sparse pca_lowrank; on TPU the randomized-SVD runs on the dense XLA
    path — sparsity is a storage property here, not a compute path)."""
    from ..tensor import linalg as _linalg

    return _linalg.pca_lowrank(x.to_dense(), q=q, center=center, niter=niter)


__all__ += [
    "asin", "asinh", "atan", "atanh", "isnan", "transpose", "coalesce",
    "is_same_shape", "sum", "reshape", "slice", "mv", "addmm", "pca_lowrank",
]
