"""paddle.text parity (SURVEY.md §2.8 datasets/text row): ViterbiDecoder +
dataset loaders.

Reference: python/paddle/text — viterbi_decode op (phi viterbi_decode
kernel) and legacy dataset loaders. Decoding is a lax.scan max-product
forward pass + backtrack, fully jittable on TPU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..autograd.engine import apply_op
from ..nn.layer.layers import Layer
from ..tensor.tensor import Tensor
from . import datasets


def viterbi_decode(potentials: Tensor, transition_params: Tensor,
                   lengths: Tensor, include_bos_eos_tag: bool = True,
                   name=None):
    """Batch Viterbi decoding (reference: paddle.text.viterbi_decode).

    potentials [B, L, C] emission scores; transition_params [C, C];
    lengths [B] valid steps per sequence. With include_bos_eos_tag, tag C-2
    is BOS and C-1 is EOS (reference contract): step 0 adds
    transition[BOS, :], the last valid step adds transition[:, EOS].
    Returns (scores [B], paths [B, L_max_valid]).
    """

    def fn(pots, trans, lens):
        B, L, C = pots.shape
        if include_bos_eos_tag:
            alpha0 = pots[:, 0] + trans[C - 2][None, :]
        else:
            alpha0 = pots[:, 0]

        def step(carry, t):
            alpha = carry  # [B, C]
            # scores[b, i, j] = alpha[b, i] + trans[i, j] + pots[b, t, j]
            scores = alpha[:, :, None] + trans[None, :, :]
            best_prev = jnp.argmax(scores, axis=1)  # [B, C]
            new_alpha = jnp.max(scores, axis=1) + pots[:, t]
            if include_bos_eos_tag:
                # at each sequence's last step, add the EOS transition; we
                # apply it lazily below by tracking per-step alphas
                pass
            # freeze alphas past each sequence's length
            active = (t < lens)[:, None]
            new_alpha = jnp.where(active, new_alpha, alpha)
            best_prev = jnp.where(active, best_prev,
                                  jnp.arange(C)[None, :])
            return new_alpha, (new_alpha, best_prev)

        alpha_final, (alphas, backptrs) = jax.lax.scan(
            step, alpha0, jnp.arange(1, L))
        if include_bos_eos_tag:
            alpha_final = alpha_final + trans[:, C - 1][None, :]
        scores = jnp.max(alpha_final, axis=1)
        last_tag = jnp.argmax(alpha_final, axis=1)  # [B]

        # backtrack from each sequence's end
        def back(carry, t_rev):
            tag = carry  # [B]
            ptrs = backptrs[t_rev]  # [B, C] for step t_rev+1
            prev_tag = jnp.take_along_axis(
                ptrs, tag[:, None], axis=1)[:, 0]
            active = (t_rev + 1) < lens
            prev_tag = jnp.where(active, prev_tag, tag)
            return prev_tag, tag

        _, path_rev = jax.lax.scan(back, last_tag,
                                   jnp.arange(L - 2, -1, -1))
        first = _  # tag at t=0
        path = jnp.concatenate([first[None], path_rev[::-1]], axis=0).T
        return scores, path.astype(jnp.int64)

    return apply_op("viterbi_decode", fn, potentials, transition_params,
                    lengths)


class ViterbiDecoder(Layer):
    """Layer wrapper (reference: paddle.text.ViterbiDecoder)."""

    def __init__(self, transitions: Tensor, include_bos_eos_tag: bool = True,
                 name=None):
        super().__init__()
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)


__all__ = ["viterbi_decode", "ViterbiDecoder", "datasets",
           "Conll05st", "Imdb", "Imikolov", "Movielens", "UCIHousing",
           "WMT14", "WMT16"]

from .tokenizer import BertTokenizer, FasterTokenizer, faster_tokenizer  # noqa: F401,E402
from .datasets import (  # noqa: F401,E402  top-level reference spellings
    Conll05st,
    Imdb,
    Imikolov,
    Movielens,
    UCIHousing,
    WMT14,
    WMT16,
)
