"""FasterTokenizer parity: host-side BERT wordpiece tokenization producing
padded device arrays.

Reference: the faster_tokenizer custom op family
(paddle/phi/kernels/funcs/string_tensor helpers + the external
PaddleNLP FasterTokenizer op that fuses basic+wordpiece tokenization into
the graph). TPU-native: tokenization is host work (ragged strings never
touch the chip); the op's contract — StringTensor in, padded
(input_ids, token_type_ids) out — is preserved so text datasets feed BERT
end-to-end without leaving the framework.
"""
from __future__ import annotations

import unicodedata

import numpy as np

from ..strings import StringTensor
from ..tensor.tensor import Tensor

__all__ = ["BertTokenizer", "FasterTokenizer", "faster_tokenizer"]


def _is_punct(ch: str) -> bool:
    cp = ord(ch)
    if (33 <= cp <= 47) or (58 <= cp <= 64) or (91 <= cp <= 96) or (123 <= cp <= 126):
        return True
    return unicodedata.category(ch).startswith("P")


def _basic_tokenize(text: str, do_lower_case: bool) -> list[str]:
    if do_lower_case:
        text = text.lower()
    out: list[str] = []
    buf = []
    for ch in text:
        if ch.isspace():
            if buf:
                out.append("".join(buf))
                buf = []
        elif _is_punct(ch):
            if buf:
                out.append("".join(buf))
                buf = []
            out.append(ch)
        else:
            buf.append(ch)
    if buf:
        out.append("".join(buf))
    return out


class BertTokenizer:
    """Greedy-longest-match wordpiece over a vocab dict (BERT convention:
    continuation pieces prefixed '##'; unknown words -> [UNK])."""

    def __init__(self, vocab: dict[str, int], do_lower_case: bool = True,
                 unk_token: str = "[UNK]", cls_token: str = "[CLS]",
                 sep_token: str = "[SEP]", pad_token: str = "[PAD]",
                 max_input_chars_per_word: int = 100):
        self.vocab = dict(vocab)
        self.do_lower_case = do_lower_case
        self.unk_token = unk_token
        self.cls_token = cls_token
        self.sep_token = sep_token
        self.pad_token = pad_token
        self.max_chars = max_input_chars_per_word
        for tok in (unk_token, cls_token, sep_token, pad_token):
            if tok not in self.vocab:
                raise ValueError(f"vocab is missing special token {tok!r}")

    @classmethod
    def from_vocab_file(cls, path: str, **kw) -> "BertTokenizer":
        vocab = {}
        with open(path, encoding="utf-8") as f:
            for i, line in enumerate(f):
                vocab[line.rstrip("\n")] = i
        return cls(vocab, **kw)

    def wordpiece(self, word: str) -> list[str]:
        if len(word) > self.max_chars:
            return [self.unk_token]
        pieces, start = [], 0
        while start < len(word):
            end = len(word)
            cur = None
            while start < end:
                sub = word[start:end]
                if start > 0:
                    sub = "##" + sub
                if sub in self.vocab:
                    cur = sub
                    break
                end -= 1
            if cur is None:
                return [self.unk_token]
            pieces.append(cur)
            start = end
        return pieces

    def tokenize(self, text: str) -> list[str]:
        out = []
        for word in _basic_tokenize(text, self.do_lower_case):
            out.extend(self.wordpiece(word))
        return out

    def __call__(self, text, text_pair=None, max_seq_len: int = 128,
                 pad_to_max_seq_len: bool = True):
        """Encode a batch: StringTensor/list[str] -> dict of device Tensors
        (input_ids, token_type_ids) padded to ``max_seq_len`` — the
        faster_tokenizer op contract."""
        if isinstance(text, StringTensor):
            text = text.numpy().reshape(-1).tolist()
        elif isinstance(text, str):
            text = [text]
        if isinstance(text_pair, StringTensor):
            text_pair = text_pair.numpy().reshape(-1).tolist()
        elif isinstance(text_pair, str):
            text_pair = [text_pair]
        B = len(text)
        ids = np.full((B, max_seq_len), self.vocab[self.pad_token], np.int64)
        segs = np.zeros((B, max_seq_len), np.int64)
        for b in range(B):
            toks = [self.cls_token] + self.tokenize(text[b]) + [self.sep_token]
            seg = [0] * len(toks)
            if text_pair is not None:
                pair = self.tokenize(text_pair[b]) + [self.sep_token]
                toks += pair
                seg += [1] * len(pair)
            toks = toks[:max_seq_len]
            seg = seg[:max_seq_len]
            row = [self.vocab.get(t, self.vocab[self.unk_token]) for t in toks]
            ids[b, :len(row)] = row
            segs[b, :len(seg)] = seg
        return {"input_ids": Tensor(ids), "token_type_ids": Tensor(segs)}


# op-shaped alias (reference: the fused faster_tokenizer op)
FasterTokenizer = BertTokenizer


def faster_tokenizer(vocab: dict[str, int], text, text_pair=None,
                     do_lower_case: bool = True, max_seq_len: int = 128):
    """Functional form of the faster_tokenizer op: returns
    (input_ids, token_type_ids) Tensors."""
    tok = BertTokenizer(vocab, do_lower_case=do_lower_case)
    out = tok(text, text_pair, max_seq_len=max_seq_len)
    return out["input_ids"], out["token_type_ids"]
