"""paddle.text.datasets parity: loaders for the classic corpora.

Reference: python/paddle/text/datasets (UCIHousing, Imdb, Imikolov,
Movielens, Conll05st, WMT14/16) — each downloads an archive then parses it.
This environment has no egress, so every loader takes ``data_file`` (a
local copy of the reference's archive/file) and parses the same formats;
with no file present a clear DownloadUnavailable error explains what to
fetch. UCIHousing additionally accepts plain whitespace-separated rows.
"""
from __future__ import annotations

import gzip
import os
import tarfile

import numpy as np

from ..io.dataset import Dataset


class DownloadUnavailable(RuntimeError):
    def __init__(self, name, url_hint):
        super().__init__(
            f"{name}: automatic download is disabled in this build "
            f"(no network egress). Pass data_file= with a local copy "
            f"of {url_hint}.")


class UCIHousing(Dataset):
    """506x13 housing regression (reference: text/datasets/uci_housing.py,
    80/20 train/test split, feature-wise max-min normalization)."""

    FEATURE_NUM = 13

    def __init__(self, data_file=None, mode="train", download=False):
        if data_file is None or not os.path.exists(data_file):
            raise DownloadUnavailable(
                "UCIHousing", "housing.data (UCI archive)")
        raw = np.loadtxt(data_file).astype("float32")
        feats = raw[:, :-1]
        maxs, mins = feats.max(0), feats.min(0)
        avgs = feats.mean(0)
        feats = (feats - avgs) / (maxs - mins + 1e-12)
        data = np.concatenate([feats, raw[:, -1:]], 1)
        split = int(len(data) * 0.8)
        self.data = data[:split] if mode == "train" else data[split:]

    def __getitem__(self, idx):
        row = self.data[idx]
        return row[:-1], row[-1:]

    def __len__(self):
        return len(self.data)


class Imdb(Dataset):
    """IMDB sentiment (reference: text/datasets/imdb.py — builds a word
    dict from the tarball, tokenizes by whitespace, label from path)."""

    def __init__(self, data_file=None, mode="train", cutoff=150,
                 download=False):
        if data_file is None or not os.path.exists(data_file):
            raise DownloadUnavailable("Imdb", "aclImdb_v1.tar.gz")
        self.mode = mode
        docs, labels = [], []
        # vocabulary spans BOTH splits (reference build_work_dict reads the
        # whole archive) so train/test token ids are consistent
        freq: dict[str, int] = {}
        with tarfile.open(data_file) as tf:
            for member in tf.getmembers():
                n = member.name
                if not n.endswith(".txt") or not (
                        n.startswith("aclImdb/train") or
                        n.startswith("aclImdb/test")):
                    continue
                if "/pos/" in n:
                    label = 0
                elif "/neg/" in n:
                    label = 1
                else:
                    continue
                text = tf.extractfile(member).read().decode(
                    "utf-8", "ignore").lower()
                toks = text.split()
                for t in toks:
                    freq[t] = freq.get(t, 0) + 1
                if n.startswith(f"aclImdb/{mode}"):
                    docs.append(toks)
                    labels.append(label)
        vocab = [w for w, c in sorted(freq.items(),
                                      key=lambda kv: (-kv[1], kv[0]))
                 if c > cutoff]
        self.word_idx = {w: i for i, w in enumerate(vocab)}
        self.word_idx["<unk>"] = len(self.word_idx)
        unk = self.word_idx["<unk>"]
        self.docs = [np.array([self.word_idx.get(t, unk) for t in d],
                              np.int64) for d in docs]
        self.labels = np.array(labels, np.int64)

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """PTB n-gram dataset (reference: text/datasets/imikolov.py)."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50, download=False):
        if data_file is None or not os.path.exists(data_file):
            raise DownloadUnavailable("Imikolov", "simple-examples.tgz")
        fname = f"./simple-examples/data/ptb.{'train' if mode == 'train' else 'valid'}.txt"
        freq: dict[str, int] = {}
        lines = []
        with tarfile.open(data_file) as tf:
            with tf.extractfile(fname) as f:
                for line in f:
                    toks = line.decode().strip().split()
                    lines.append(toks)
                    for t in toks:
                        freq[t] = freq.get(t, 0) + 1
        vocab = sorted((w for w, c in freq.items()
                        if c >= min_word_freq and w != "<unk>"))
        self.word_idx = {w: i for i, w in enumerate(vocab)}
        unk = self.word_idx.setdefault("<unk>", len(self.word_idx))
        self.data = []
        for toks in lines:
            ids = [self.word_idx.get(t, unk) for t in ["<s>"] * (window_size - 1) + toks + ["<e>"]
                   if True]
            for i in range(window_size, len(ids) + 1):
                self.data.append(np.array(ids[i - window_size: i], np.int64))

    def __getitem__(self, idx):
        row = self.data[idx]
        return tuple(row)

    def __len__(self):
        return len(self.data)


__all__ = ["UCIHousing", "Imdb", "Imikolov", "DownloadUnavailable"]


class Movielens(Dataset):
    """MovieLens-1M ratings (reference text/datasets/movielens.py): yields
    (user_id, gender_id, age_id, job_id, movie_id, category_ids, title_ids,
    rating) per rating row, parsed from the ml-1m archive's
    users/movies/ratings .dat files."""

    AGES = [1, 18, 25, 35, 45, 50, 56]

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0, download=False):
        import zipfile

        if data_file is None or not os.path.exists(data_file):
            raise DownloadUnavailable("Movielens", "ml-1m.zip")
        users, movies, ratings = {}, {}, []
        categories, title_vocab = {}, {}
        with zipfile.ZipFile(data_file) as zf:
            base = next(n for n in zf.namelist() if n.endswith("users.dat"))
            root = base[: -len("users.dat")]
            with zf.open(root + "users.dat") as f:
                for line in f.read().decode("latin1").splitlines():
                    uid, gender, age, job, _ = line.strip().split("::")
                    users[int(uid)] = (0 if gender == "M" else 1,
                                       self.AGES.index(int(age)), int(job))
            with zf.open(root + "movies.dat") as f:
                for line in f.read().decode("latin1").splitlines():
                    mid, title, cats = line.strip().split("::")
                    cat_ids = []
                    for c in cats.split("|"):
                        cat_ids.append(categories.setdefault(c, len(categories)))
                    tit_ids = []
                    for w in title.lower().split():
                        tit_ids.append(title_vocab.setdefault(w, len(title_vocab)))
                    movies[int(mid)] = (cat_ids, tit_ids)
            with zf.open(root + "ratings.dat") as f:
                for line in f.read().decode("latin1").splitlines():
                    uid, mid, rating, _ = line.strip().split("::")
                    uid, mid = int(uid), int(mid)
                    if uid in users and mid in movies:
                        ratings.append((uid, mid, float(rating)))
        rng = np.random.RandomState(rand_seed)
        mask = rng.rand(len(ratings)) < (1.0 - test_ratio)
        keep = mask if mode == "train" else ~mask
        self._rows = [r for r, k in zip(ratings, keep) if k]
        self._users, self._movies = users, movies
        self.categories_dict, self.movie_title_dict = categories, title_vocab

    def __getitem__(self, idx):
        uid, mid, rating = self._rows[idx]
        gender, age, job = self._users[uid]
        cats, title = self._movies[mid]
        return (np.int64(uid), np.int64(gender), np.int64(age),
                np.int64(job), np.int64(mid),
                np.asarray(cats, np.int64), np.asarray(title, np.int64),
                np.float32(rating))

    def __len__(self):
        return len(self._rows)


class _ParallelCorpus(Dataset):
    """Shared WMT14/WMT16 machinery: parallel src/trg lines from a tarball,
    frequency-cut vocabularies with <s>/<e>/<unk> reserved ids 0/1/2, yields
    (src_ids, trg_ids[:-1], trg_ids[1:]) (reference text/datasets/wmt14.py
    contract)."""

    BOS, EOS, UNK = 0, 1, 2

    def __init__(self, data_file, members, dict_size, name, url_hint):
        if data_file is None or not os.path.exists(data_file):
            raise DownloadUnavailable(name, url_hint)
        src_lines, trg_lines = self._read_pairs(data_file, members)
        # dict_size: one int for both sides (WMT14), or a (src, trg) pair —
        # WMT16 exposes independent src/trg vocabulary budgets
        src_size, trg_size = (dict_size if isinstance(dict_size, (tuple, list))
                              else (dict_size, dict_size))
        self.src_dict = self._build_dict(src_lines, src_size)
        self.trg_dict = self._build_dict(trg_lines, trg_size)
        self.data = []
        for s, t in zip(src_lines, trg_lines):
            sid = [self.src_dict.get(w, self.UNK) for w in s.split()]
            tid = ([self.BOS]
                   + [self.trg_dict.get(w, self.UNK) for w in t.split()]
                   + [self.EOS])
            if sid and len(tid) > 2:
                self.data.append((np.asarray(sid, np.int64),
                                  np.asarray(tid[:-1], np.int64),
                                  np.asarray(tid[1:], np.int64)))

    @staticmethod
    def _read_pairs(data_file, members):
        src_lines, trg_lines = [], []
        with tarfile.open(data_file) as tf:
            names = tf.getnames()
            src_m = next((n for n in names if n.endswith(members[0])), None)
            trg_m = next((n for n in names if n.endswith(members[1])), None)
            if src_m is None or trg_m is None:
                raise ValueError(
                    f"archive lacks parallel members {members}; has {names[:8]}")
            with tf.extractfile(src_m) as f:
                src_lines = f.read().decode("utf-8", "replace").splitlines()
            with tf.extractfile(trg_m) as f:
                trg_lines = f.read().decode("utf-8", "replace").splitlines()
        return src_lines, trg_lines

    def _build_dict(self, lines, dict_size):
        freq: dict[str, int] = {}
        for line in lines:
            for w in line.split():
                freq[w] = freq.get(w, 0) + 1
        ranked = sorted(freq.items(), key=lambda kv: (-kv[1], kv[0]))
        vocab = {"<s>": self.BOS, "<e>": self.EOS, "<unk>": self.UNK}
        for w, _ in ranked[: max(dict_size - 3, 0)]:
            vocab.setdefault(w, len(vocab))
        return vocab

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)


class WMT14(_ParallelCorpus):
    """WMT14 en->fr (reference text/datasets/wmt14.py)."""

    def __init__(self, data_file=None, mode="train", dict_size=30000,
                 download=False):
        part = {"train": "train", "test": "test", "gen": "gen"}[mode]
        super().__init__(data_file, (f"{part}.en", f"{part}.fr"),
                         dict_size, "WMT14", "wmt14 parallel corpus tarball")


class WMT16(_ParallelCorpus):
    """WMT16 en<->de with selectable language direction (reference
    text/datasets/wmt16.py)."""

    def __init__(self, data_file=None, mode="train", src_dict_size=30000,
                 trg_dict_size=30000, lang="en", download=False):
        part = {"train": "train", "test": "test", "val": "val"}[mode]
        other = "de" if lang == "en" else "en"
        self._sizes = (src_dict_size, trg_dict_size)
        super().__init__(data_file, (f"{part}.{lang}", f"{part}.{other}"),
                         (src_dict_size, trg_dict_size), "WMT16",
                         "wmt16 en-de tarball")


class Conll05st(Dataset):
    """CoNLL-2005 semantic-role-labeling dataset (reference
    text/datasets/conll05.py): per (sentence, predicate) pair yields the
    word/context/mark feature ids + the BIO label ids. The archive must
    contain the words file and the props file (one token per line, blank
    line between sentences — the release's test.wsj layout)."""

    def __init__(self, data_file=None, word_dict_file=None,
                 verb_dict_file=None, target_dict_file=None, emb_file=None,
                 mode="test", download=False):
        if data_file is None or not os.path.exists(data_file):
            raise DownloadUnavailable(
                "Conll05st", "conll05st-tests.tar.gz (words + props files)")
        words_txt, props_txt = self._extract(data_file)
        sentences = self._split_blank(words_txt)
        props = self._split_blank(props_txt)
        self.word_dict = self._vocab(w for s in sentences for w in s)
        samples = []
        for sent, prop in zip(sentences, props):
            cols = [p.split() for p in prop]
            if not cols:
                continue
            n_preds = len(cols[0]) - 1
            preds = [c[0] for c in cols]
            for k in range(n_preds):
                tags = self._bio([c[1 + k] for c in cols])
                verb_idx = next((i for i, p in enumerate(preds)
                                 if p != "-"), 0)
                samples.append((sent, verb_idx, tags))
        self.verb_dict = self._vocab(s[0][s[1]] for s in samples)
        self.label_dict = self._vocab(t for s in samples for t in s[2])
        self._samples = samples

    @staticmethod
    def _extract(data_file):
        with tarfile.open(data_file) as tf:
            names = tf.getnames()
            wname = next((n for n in names if "words" in n), None)
            pname = next((n for n in names if "props" in n), None)
            if wname is None or pname is None:
                raise ValueError(
                    f"archive lacks words/props members; has {names[:8]}")

            def read(n):
                with tf.extractfile(n) as f:
                    data = f.read()
                if n.endswith(".gz"):
                    import gzip

                    data = gzip.decompress(data)
                return data.decode("utf-8", "replace")

            return read(wname), read(pname)

    @staticmethod
    def _split_blank(text):
        groups, cur = [], []
        for line in text.splitlines():
            if line.strip():
                cur.append(line.strip())
            elif cur:
                groups.append(cur)
                cur = []
        if cur:
            groups.append(cur)
        return groups

    @staticmethod
    def _vocab(tokens):
        vocab: dict[str, int] = {}
        for t in tokens:
            vocab.setdefault(t, len(vocab))
        return vocab

    @staticmethod
    def _bio(col):
        """Expand the CoNLL star-bracket spans into B-/I-/O tags."""
        tags, cur = [], None
        for tok in col:
            if tok.startswith("("):
                cur = tok.strip("()*")
                tags.append(f"B-{cur}")
            elif cur is not None:
                tags.append(f"I-{cur}")
            else:
                tags.append("O")
            if tok.endswith(")"):
                cur = None
        return tags

    def __getitem__(self, idx):
        sent, verb_idx, tags = self._samples[idx]
        unk = len(self.word_dict)
        word_ids = np.asarray(
            [self.word_dict.get(w, unk) for w in sent], np.int64)
        mark = np.zeros(len(sent), np.int64)
        mark[verb_idx] = 1
        verb_id = np.int64(self.verb_dict.get(sent[verb_idx], 0))
        labels = np.asarray([self.label_dict[t] for t in tags], np.int64)
        return word_ids, verb_id, mark, labels

    def __len__(self):
        return len(self._samples)

    def get_dict(self):
        return self.word_dict, self.verb_dict, self.label_dict


__all__ += ["Movielens", "WMT14", "WMT16", "Conll05st"]
