"""paddle.text.datasets parity: loaders for the classic corpora.

Reference: python/paddle/text/datasets (UCIHousing, Imdb, Imikolov,
Movielens, Conll05st, WMT14/16) — each downloads an archive then parses it.
This environment has no egress, so every loader takes ``data_file`` (a
local copy of the reference's archive/file) and parses the same formats;
with no file present a clear DownloadUnavailable error explains what to
fetch. UCIHousing additionally accepts plain whitespace-separated rows.
"""
from __future__ import annotations

import gzip
import os
import tarfile

import numpy as np

from ..io.dataset import Dataset


class DownloadUnavailable(RuntimeError):
    def __init__(self, name, url_hint):
        super().__init__(
            f"{name}: automatic download is disabled in this build "
            f"(no network egress). Pass data_file= with a local copy "
            f"of {url_hint}.")


class UCIHousing(Dataset):
    """506x13 housing regression (reference: text/datasets/uci_housing.py,
    80/20 train/test split, feature-wise max-min normalization)."""

    FEATURE_NUM = 13

    def __init__(self, data_file=None, mode="train", download=False):
        if data_file is None or not os.path.exists(data_file):
            raise DownloadUnavailable(
                "UCIHousing", "housing.data (UCI archive)")
        raw = np.loadtxt(data_file).astype("float32")
        feats = raw[:, :-1]
        maxs, mins = feats.max(0), feats.min(0)
        avgs = feats.mean(0)
        feats = (feats - avgs) / (maxs - mins + 1e-12)
        data = np.concatenate([feats, raw[:, -1:]], 1)
        split = int(len(data) * 0.8)
        self.data = data[:split] if mode == "train" else data[split:]

    def __getitem__(self, idx):
        row = self.data[idx]
        return row[:-1], row[-1:]

    def __len__(self):
        return len(self.data)


class Imdb(Dataset):
    """IMDB sentiment (reference: text/datasets/imdb.py — builds a word
    dict from the tarball, tokenizes by whitespace, label from path)."""

    def __init__(self, data_file=None, mode="train", cutoff=150,
                 download=False):
        if data_file is None or not os.path.exists(data_file):
            raise DownloadUnavailable("Imdb", "aclImdb_v1.tar.gz")
        self.mode = mode
        docs, labels = [], []
        # vocabulary spans BOTH splits (reference build_work_dict reads the
        # whole archive) so train/test token ids are consistent
        freq: dict[str, int] = {}
        with tarfile.open(data_file) as tf:
            for member in tf.getmembers():
                n = member.name
                if not n.endswith(".txt") or not (
                        n.startswith("aclImdb/train") or
                        n.startswith("aclImdb/test")):
                    continue
                if "/pos/" in n:
                    label = 0
                elif "/neg/" in n:
                    label = 1
                else:
                    continue
                text = tf.extractfile(member).read().decode(
                    "utf-8", "ignore").lower()
                toks = text.split()
                for t in toks:
                    freq[t] = freq.get(t, 0) + 1
                if n.startswith(f"aclImdb/{mode}"):
                    docs.append(toks)
                    labels.append(label)
        vocab = [w for w, c in sorted(freq.items(),
                                      key=lambda kv: (-kv[1], kv[0]))
                 if c > cutoff]
        self.word_idx = {w: i for i, w in enumerate(vocab)}
        self.word_idx["<unk>"] = len(self.word_idx)
        unk = self.word_idx["<unk>"]
        self.docs = [np.array([self.word_idx.get(t, unk) for t in d],
                              np.int64) for d in docs]
        self.labels = np.array(labels, np.int64)

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """PTB n-gram dataset (reference: text/datasets/imikolov.py)."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50, download=False):
        if data_file is None or not os.path.exists(data_file):
            raise DownloadUnavailable("Imikolov", "simple-examples.tgz")
        fname = f"./simple-examples/data/ptb.{'train' if mode == 'train' else 'valid'}.txt"
        freq: dict[str, int] = {}
        lines = []
        with tarfile.open(data_file) as tf:
            with tf.extractfile(fname) as f:
                for line in f:
                    toks = line.decode().strip().split()
                    lines.append(toks)
                    for t in toks:
                        freq[t] = freq.get(t, 0) + 1
        vocab = sorted((w for w, c in freq.items()
                        if c >= min_word_freq and w != "<unk>"))
        self.word_idx = {w: i for i, w in enumerate(vocab)}
        unk = self.word_idx.setdefault("<unk>", len(self.word_idx))
        self.data = []
        for toks in lines:
            ids = [self.word_idx.get(t, unk) for t in ["<s>"] * (window_size - 1) + toks + ["<e>"]
                   if True]
            for i in range(window_size, len(ids) + 1):
                self.data.append(np.array(ids[i - window_size: i], np.int64))

    def __getitem__(self, idx):
        row = self.data[idx]
        return tuple(row)

    def __len__(self):
        return len(self.data)


__all__ = ["UCIHousing", "Imdb", "Imikolov", "DownloadUnavailable"]
