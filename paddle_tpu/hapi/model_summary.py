"""Model summary (parity: python/paddle/hapi/model_summary.py)."""
from __future__ import annotations

import numpy as np

from ..nn import Layer
from ..tensor.tensor import Tensor


def summary(net: Layer, input_size=None, dtypes=None, input=None):
    """Print a per-layer table (name, output shape, params) and return
    {'total_params', 'trainable_params'}."""
    rows = []
    hooks = []

    def register(layer, prefix):
        def hook(l, inputs, outputs):
            outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
            shape = [list(o.shape) for o in outs if isinstance(o, Tensor)]
            n_params = sum(int(np.prod(p.shape)) for p in l._parameters.values())
            rows.append((prefix or l.__class__.__name__, shape, n_params))

        hooks.append(layer.register_forward_post_hook(hook))

    if input is None and input_size is None:
        raise ValueError("summary needs input_size or input")

    for name, sub in net.named_sublayers():
        register(sub, name)

    if input is not None:
        x = input if isinstance(input, (list, tuple)) else [input]
    elif input_size is not None:
        sizes = input_size if isinstance(input_size, list) and isinstance(input_size[0], (list, tuple)) else [input_size]
        dts = dtypes if isinstance(dtypes, (list, tuple)) else [dtypes] * len(sizes)
        x = [
            Tensor(np.zeros([d if d is not None else 1 for d in s], (dt or "float32")))
            for s, dt in zip(sizes, dts)
        ]
    else:
        raise ValueError("summary needs input_size or input")

    was_training = net.training
    net.eval()
    try:
        net(*x)
    finally:
        if was_training:
            net.train()
        for h in hooks:
            h.remove()

    total = sum(int(np.prod(p.shape)) for p in net.parameters())
    trainable = sum(
        int(np.prod(p.shape)) for p in net.parameters() if not p.stop_gradient
    )
    line = "-" * 72
    print(line)
    print(f"{'Layer (type)':<32}{'Output Shape':<24}{'Param #':<12}")
    print(line)
    for name, shape, n in rows:
        print(f"{name:<32}{str(shape):<24}{n:<12}")
    print(line)
    print(f"Total params: {total:,}")
    print(f"Trainable params: {trainable:,}")
    print(line)
    return {"total_params": total, "trainable_params": trainable}
