"""Model summary (parity: python/paddle/hapi/model_summary.py)."""
from __future__ import annotations

import numpy as np

from ..framework import dtype as dtype_mod
from ..nn import Layer
from ..tensor.tensor import Tensor


def summary(net: Layer, input_size=None, dtypes=None, input=None):
    """Print a per-layer table (name, output shape, params) and return
    {'total_params', 'trainable_params'}."""
    rows = []
    hooks = []

    def register(layer, prefix):
        def hook(l, inputs, outputs):
            outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
            shape = [list(o.shape) for o in outs if isinstance(o, Tensor)]
            n_params = sum(int(np.prod(p.shape)) for p in l._parameters.values())
            rows.append((prefix or l.__class__.__name__, shape, n_params))

        hooks.append(layer.register_forward_post_hook(hook))

    if input is None and input_size is None:
        raise ValueError("summary needs input_size or input")

    for name, sub in net.named_sublayers():
        register(sub, name)

    if input is not None:
        x = input if isinstance(input, (list, tuple)) else [input]
    elif input_size is not None:
        sizes = input_size if isinstance(input_size, list) and isinstance(input_size[0], (list, tuple)) else [input_size]
        dts = dtypes if isinstance(dtypes, (list, tuple)) else [dtypes] * len(sizes)
        x = [
            Tensor(np.zeros([d if d is not None else 1 for d in s], (dt or "float32")))
            for s, dt in zip(sizes, dts)
        ]
    else:
        raise ValueError("summary needs input_size or input")

    was_training = net.training
    net.eval()
    try:
        net(*x)
    finally:
        if was_training:
            net.train()
        for h in hooks:
            h.remove()

    total = sum(int(np.prod(p.shape)) for p in net.parameters())
    trainable = sum(
        int(np.prod(p.shape)) for p in net.parameters() if not p.stop_gradient
    )
    line = "-" * 72
    print(line)
    print(f"{'Layer (type)':<32}{'Output Shape':<24}{'Param #':<12}")
    print(line)
    for name, shape, n in rows:
        print(f"{name:<32}{str(shape):<24}{n:<12}")
    print(line)
    print(f"Total params: {total:,}")
    print(f"Trainable params: {trainable:,}")
    print(line)
    return {"total_params": total, "trainable_params": trainable}


def flops(net, input_size, custom_ops=None, print_detail=False, *,
          dtype="float32"):
    """Model FLOPs estimate via forward hooks (reference:
    paddle.flops / hapi/dynamic_flops.py). Counts multiply-accumulates as
    2 FLOPs for Linear/Conv; norms/activations count one pass."""
    from .. import nn

    total = [0]
    detail = []
    custom_ops = custom_ops or {}

    def count(layer, inputs, output):
        t = type(layer)
        n = 0
        out = output[0] if isinstance(output, (tuple, list)) else output
        out_numel = int(np.prod(out.shape)) if hasattr(out, "shape") else 0
        if t in custom_ops:
            n = custom_ops[t](layer, inputs, output)
        elif isinstance(layer, nn.Linear):
            n = 2 * out_numel * layer.in_features
        elif isinstance(layer, (nn.Conv2D, nn.Conv3D, nn.Conv1D)):
            w = layer.weight
            k_numel = int(np.prod(w.shape[1:]))  # cin/groups * prod(k)
            n = 2 * out_numel * k_numel
        elif isinstance(layer, (nn.BatchNorm1D, nn.BatchNorm2D,
                                nn.BatchNorm3D, nn.LayerNorm)):
            n = 2 * out_numel
        elif isinstance(layer, (nn.ReLU, nn.GELU, nn.Sigmoid, nn.Tanh)):
            n = out_numel
        if n:
            total[0] += n
            detail.append((layer.full_name() if hasattr(layer, "full_name")
                           else type(layer).__name__, n))

    handles = []
    # include_self: a bare layer (no sublayers) must count itself
    for _, sub in net.named_sublayers(include_self=True):
        handles.append(sub.register_forward_post_hook(count))
    try:
        import jax.numpy as jnp

        x = Tensor(jnp.zeros(tuple(input_size),
                             dtype_mod.to_jax_dtype(dtype)))
        was_training = net.training
        net.eval()
        try:
            net(x)
        finally:
            if was_training:
                net.train()
    finally:
        for h in handles:
            h.remove()
    if print_detail:
        for name, n in detail:
            print(f"{name:<40s} {n:>16,d}")
        print(f"{'Total':<40s} {total[0]:>16,d}")
    return total[0]
