"""paddle.Model — the Keras-like train loop.

Parity: python/paddle/hapi/model.py (prepare :1676, fit :1756, evaluate,
predict, save/load :1054, train_batch/eval_batch). Dynamic-mode
implementation; the jit path comes from wrapping the network with
paddle.jit.to_static before constructing the Model.
"""
from __future__ import annotations

import os

import numpy as np

from .. import framework_io
from ..io import DataLoader
from ..metric import Metric
from ..tensor.tensor import Tensor
from .callbacks import Callback, CallbackList, ModelCheckpoint, ProgBarLogger


def _to_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self.stop_training = False

    # --- configuration -----------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None):
        """Configure the model (reference hapi/model.py:1295 prepare).

        Distributed-aware (reference model.py:225 init context): when the
        parallel env is initialized with world size > 1 the network is
        wrapped in DataParallel so fit/train_batch sync gradients.

        Static-graph-aware (reference's static-mode adapter): when
        ``paddle.enable_static()`` is active, forward/loss execute through
        ONE compiled program per input signature via jit.to_static — the
        TPU-native equivalent of the reference's static _run path.
        """
        self._optimizer = optimizer
        self._loss = loss
        for m in _to_list(metrics):
            if not isinstance(m, Metric):
                raise TypeError(f"metric must be paddle.metric.Metric, got {type(m)}")
        self._metrics = _to_list(metrics)

        import paddle_tpu as paddle
        from ..distributed import is_initialized

        if is_initialized():
            # nranks = the default group's extent (devices on the
            # single-controller runtime, processes×devices on multi-host) —
            # the reference keys on ParallelEnv().nranks the same way
            from ..distributed.collective import _init_default_group
            from ..distributed.parallel import DataParallel

            nranks = _init_default_group().nranks
            if nranks > 1 and not isinstance(self.network, DataParallel):
                self.network = DataParallel(self.network)
        if not paddle.in_dynamic_mode():
            from ..jit import to_static

            if not getattr(self.network.forward, "__wrapped__", None):
                self.network = to_static(self.network)

    # --- single-batch ops --------------------------------------------------
    def _forward(self, inputs):
        ins = _to_list(inputs)
        ins = [x if isinstance(x, Tensor) else Tensor(np.asarray(x)) for x in ins]
        outs = self.network(*ins)
        return _to_list(outs)

    def train_batch(self, inputs, labels=None, update=True):
        self.network.train()
        outputs = self._forward(inputs)
        labels_t = [
            y if isinstance(y, Tensor) else Tensor(np.asarray(y))
            for y in _to_list(labels)
        ]
        losses = _to_list(self._loss(*(outputs + labels_t)))
        total = losses[0]
        for extra in losses[1:]:
            total = total + extra
        total.backward()
        if update:
            self._optimizer.step()
            self._optimizer.clear_grad()
        metrics = []
        for m in self._metrics:
            m.update(*[t.numpy() if isinstance(t, Tensor) else t for t in m.compute(*(outputs + labels_t))])
            metrics.append(m.accumulate())
        out = [float(l.numpy()) for l in losses]
        return (out, metrics) if metrics else out

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        from ..autograd import no_grad

        with no_grad():
            outputs = self._forward(inputs)
            labels_t = [
                y if isinstance(y, Tensor) else Tensor(np.asarray(y))
                for y in _to_list(labels)
            ]
            losses = (
                _to_list(self._loss(*(outputs + labels_t))) if self._loss else []
            )
            metrics = []
            for m in self._metrics:
                m.update(*[t.numpy() if isinstance(t, Tensor) else t for t in m.compute(*(outputs + labels_t))])
                metrics.append(m.accumulate())
        out = [float(l.numpy()) for l in losses]
        return (out, metrics) if metrics else out

    def predict_batch(self, inputs):
        self.network.eval()
        from ..autograd import no_grad

        with no_grad():
            outputs = self._forward(inputs)
        return [o.numpy() for o in outputs]

    # --- loops -------------------------------------------------------------
    def _make_loader(self, data, batch_size, shuffle, drop_last=False,
                     num_workers=0):
        if data is None or isinstance(data, DataLoader):
            return data
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                          drop_last=drop_last, num_workers=num_workers)

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None):
        loader = self._make_loader(train_data, batch_size, shuffle,
                                   drop_last=drop_last,
                                   num_workers=num_workers)
        eval_loader = self._make_loader(eval_data, batch_size, False,
                                        num_workers=num_workers)
        cbks = _to_list(callbacks) or [ProgBarLogger(log_freq, verbose=verbose)]
        if save_dir:
            cbks.append(ModelCheckpoint(save_freq, save_dir))
        cb = CallbackList(cbks)
        cb.set_model(self)
        cb.set_params({"epochs": epochs, "steps": len(loader),
                       "verbose": verbose, "save_dir": save_dir})
        self.stop_training = False

        cb.on_train_begin()
        logs = {}
        for epoch in range(epochs):
            cb.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            logs = {}
            for step, batch in enumerate(loader):
                if num_iters is not None and step >= num_iters:
                    break
                cb.on_train_batch_begin(step)
                ins, labs = self._split_batch(batch)
                update = (step + 1) % accumulate_grad_batches == 0
                res = self.train_batch(ins, labs, update=update)
                logs = self._logs_from(res)
                cb.on_train_batch_end(step, logs)
                if self.stop_training:
                    break
            cb.on_epoch_end(epoch, logs)
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                self._run_eval(eval_loader, cb)
            if self.stop_training:
                break
        cb.on_train_end(logs)

    def _split_batch(self, batch):
        if isinstance(batch, (list, tuple)) and len(batch) >= 2:
            return batch[:-1], batch[-1:]
        return batch, None

    def _logs_from(self, res):
        logs = {}
        if isinstance(res, tuple):
            losses, metrics = res
            logs["loss"] = losses
            for m, v in zip(self._metrics, metrics):
                names = m.name() if isinstance(m.name(), (list, tuple)) else [m.name()]
                vals = v if isinstance(v, (list, tuple)) else [v]
                for n, val in zip(names, vals):
                    logs[n] = val
        else:
            logs["loss"] = res
        return logs

    def _run_eval(self, loader, cb):
        cb.on_eval_begin()
        for m in self._metrics:
            m.reset()
        logs = {}
        for step, batch in enumerate(loader):
            cb.on_eval_batch_begin(step)
            ins, labs = self._split_batch(batch)
            res = self.eval_batch(ins, labs)
            logs = self._logs_from(res)
            cb.on_eval_batch_end(step, logs)
        cb.on_eval_end(logs)
        return logs

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_iters=None):
        loader = self._make_loader(eval_data, batch_size, False)
        cb = CallbackList(_to_list(callbacks) or [ProgBarLogger(log_freq, verbose=verbose)])
        cb.set_model(self)
        cb.set_params({"steps": len(loader), "verbose": verbose})
        return self._run_eval(loader, cb)

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False, callbacks=None, verbose=1):
        loader = self._make_loader(test_data, batch_size, False)
        outputs = []
        for batch in loader:
            ins = batch[0] if isinstance(batch, (list, tuple)) else batch
            outputs.append(self.predict_batch([ins]))
        if stack_outputs:
            n_out = len(outputs[0])
            return [np.concatenate([o[i] for o in outputs]) for i in range(n_out)]
        return outputs

    # --- persistence -------------------------------------------------------
    def save(self, path, training=True):
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        if training:
            framework_io.save(self.network.state_dict(), path + ".pdparams")
            if self._optimizer is not None:
                framework_io.save(self._optimizer.state_dict(), path + ".pdopt")
        else:
            from ..jit import save as jit_save
            from ..jit.api import InputSpec

            specs = self._inputs
            jit_save(self.network, path, input_spec=specs)

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        state = framework_io.load(path + ".pdparams")
        self.network.set_state_dict(state)
        if not reset_optimizer and self._optimizer is not None and os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(framework_io.load(path + ".pdopt"))

    def parameters(self, *a, **k):
        return self.network.parameters(*a, **k)

    def summary(self, input_size=None, dtype=None):
        from .model_summary import summary

        return summary(self.network, input_size, dtypes=dtype)
