"""hapi callbacks (parity: python/paddle/hapi/callbacks.py — ProgBarLogger,
ModelCheckpoint, LRScheduler, EarlyStopping)."""
from __future__ import annotations

import numbers
import os
import time

import numpy as np


class Callback:
    def set_params(self, params):
        self.params = params

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None): ...
    def on_train_end(self, logs=None): ...
    def on_eval_begin(self, logs=None): ...
    def on_eval_end(self, logs=None): ...
    def on_predict_begin(self, logs=None): ...
    def on_predict_end(self, logs=None): ...
    def on_epoch_begin(self, epoch, logs=None): ...
    def on_epoch_end(self, epoch, logs=None): ...
    def on_train_batch_begin(self, step, logs=None): ...
    def on_train_batch_end(self, step, logs=None): ...
    def on_eval_batch_begin(self, step, logs=None): ...
    def on_eval_batch_end(self, step, logs=None): ...
    def on_predict_batch_begin(self, step, logs=None): ...
    def on_predict_batch_end(self, step, logs=None): ...


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = list(callbacks)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def __getattr__(self, name):
        if name.startswith("on_"):
            def call(*args, **kwargs):
                for c in self.callbacks:
                    getattr(c, name)(*args, **kwargs)

            return call
        raise AttributeError(name)


class ProgBarLogger(Callback):
    """Prints per-epoch progress with loss/metrics and throughput."""

    def __init__(self, log_freq: int = 1, verbose: int = 2):
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = (self.params or {}).get("steps")
        self._t0 = time.time()
        if self.verbose:
            print(f"Epoch {epoch + 1}/{self.params.get('epochs', '?')}")

    def _fmt(self, logs):
        items = []
        for k, v in (logs or {}).items():
            if isinstance(v, (numbers.Number, np.floating)):
                items.append(f"{k}: {float(v):.4f}")
            elif isinstance(v, (list, np.ndarray)) and np.size(v) == 1:
                items.append(f"{k}: {float(np.ravel(v)[0]):.4f}")
        return " - ".join(items)

    def on_train_batch_end(self, step, logs=None):
        if self.verbose > 1 and step % self.log_freq == 0:
            print(f"step {step}: {self._fmt(logs)}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._t0
            print(f"epoch {epoch + 1} done in {dt:.1f}s - {self._fmt(logs)}")

    def on_eval_end(self, logs=None):
        if self.verbose:
            print(f"Eval - {self._fmt(logs)}")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq: int = 1, save_dir: str | None = None):
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        self.by_step, self.by_epoch = by_step, by_epoch

    def _sched(self):
        opt = self.model._optimizer
        lr = getattr(opt, "_learning_rate", None)
        from ..optimizer.lr import LRScheduler as Sched

        return lr if isinstance(lr, Sched) else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()


def _monitor_value(logs, monitor):
    cur = (logs or {}).get(monitor)
    if cur is None:
        return None
    if not isinstance(cur, numbers.Number):
        cur = float(np.ravel(cur)[0])
    return float(cur)


def _is_better(cur, best, mode, min_delta):
    if best is None:
        return True
    if mode == "min":
        return cur < best - min_delta
    return cur > best + min_delta


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self.stopped_epoch = 0
        self.wait = 0
        self.best = None
        self.stop_training = False
        self.save_dir = None  # filled from fit(save_dir=...) via set_params

    def set_params(self, params):
        super().set_params(params)
        if isinstance(params, dict) and params.get("save_dir"):
            self.save_dir = params["save_dir"]

    def _better(self, cur, best):
        return _is_better(cur, best, self.mode, self.min_delta)

    def on_eval_end(self, logs=None):
        cur = _monitor_value(logs, self.monitor)
        if cur is None:
            return
        if self._better(cur, self.best):
            self.best = cur
            self.wait = 0
            if self.save_best_model and self.save_dir is not None:
                self.model.save(os.path.join(self.save_dir, "best_model"))
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stop_training = True
                self.model.stop_training = True


class ReduceLROnPlateau(Callback):
    """Reduce the optimizer LR when the monitored metric plateaus
    (reference hapi/callbacks.py ReduceLROnPlateau)."""

    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1,
                 mode="auto", min_delta=1e-4, cooldown=0, min_lr=0):
        self.monitor = monitor
        self.factor = float(factor)
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.cooldown = cooldown
        self.min_lr = min_lr
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self.best = None
        self.wait = 0
        self.cooldown_counter = 0

    def _better(self, cur, best):
        return _is_better(cur, best, self.mode, self.min_delta)

    def on_eval_end(self, logs=None):
        cur = _monitor_value(logs, self.monitor)
        if cur is None:
            return
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.wait = 0
        if self._better(cur, self.best):
            self.best = cur
            self.wait = 0
            return
        if self.cooldown_counter > 0:
            return
        self.wait += 1
        if self.wait >= self.patience:
            opt = self.model._optimizer
            from ..optimizer.lr import LRScheduler as Sched

            lr = getattr(opt, "_learning_rate", None)
            if isinstance(lr, Sched):
                self.wait = 0  # scheduler owns the lr; reference skips too
                return
            old = float(lr)
            new = max(old * self.factor, self.min_lr)
            if new < old:
                opt.set_lr(new)
                if self.verbose:
                    print(f"ReduceLROnPlateau: lr {old:.3e} -> {new:.3e}")
            self.wait = 0
            self.cooldown_counter = self.cooldown


class VisualDL(Callback):
    """VisualDL scalar logging (reference hapi/callbacks.py VisualDL).
    The visualdl package is not in this build — the callback degrades to a
    JSONL metric log at the same path (loadable by any dashboard)."""

    def __init__(self, log_dir):
        self.log_dir = log_dir
        self._step = 0

    def _write(self, tag, logs):
        import json

        os.makedirs(self.log_dir, exist_ok=True)
        rec = {"tag": tag, "step": self._step}
        for k, v in (logs or {}).items():
            if isinstance(v, numbers.Number):
                rec[k] = float(v)
            else:
                try:
                    rec[k] = float(np.ravel(v)[0])
                except Exception:
                    continue
        with open(os.path.join(self.log_dir, "scalars.jsonl"), "a") as f:
            f.write(json.dumps(rec) + "\n")

    def on_train_batch_end(self, step, logs=None):
        self._step += 1

    def on_epoch_end(self, epoch, logs=None):
        self._write("train", logs)

    def on_eval_end(self, logs=None):
        self._write("eval", logs)


class WandbCallback(Callback):
    """Weights & Biases logging (reference hapi/callbacks.py
    WandbCallback). Requires the external wandb package; raises with
    guidance when absent (no silent no-op)."""

    def __init__(self, project=None, dir=None, **kwargs):
        try:
            import wandb
        except ImportError as e:
            raise ImportError(
                "WandbCallback requires the 'wandb' package, which is not "
                "available in this build — use the VisualDL callback's "
                "JSONL output or a custom Callback instead") from e
        self._run = wandb.init(project=project, dir=dir, **kwargs)

    def on_epoch_end(self, epoch, logs=None):
        self._run.log({k: v for k, v in (logs or {}).items()
                       if isinstance(v, numbers.Number)})
