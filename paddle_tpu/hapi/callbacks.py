"""hapi callbacks (parity: python/paddle/hapi/callbacks.py — ProgBarLogger,
ModelCheckpoint, LRScheduler, EarlyStopping)."""
from __future__ import annotations

import numbers
import os
import time

import numpy as np


class Callback:
    def set_params(self, params):
        self.params = params

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None): ...
    def on_train_end(self, logs=None): ...
    def on_eval_begin(self, logs=None): ...
    def on_eval_end(self, logs=None): ...
    def on_predict_begin(self, logs=None): ...
    def on_predict_end(self, logs=None): ...
    def on_epoch_begin(self, epoch, logs=None): ...
    def on_epoch_end(self, epoch, logs=None): ...
    def on_train_batch_begin(self, step, logs=None): ...
    def on_train_batch_end(self, step, logs=None): ...
    def on_eval_batch_begin(self, step, logs=None): ...
    def on_eval_batch_end(self, step, logs=None): ...
    def on_predict_batch_begin(self, step, logs=None): ...
    def on_predict_batch_end(self, step, logs=None): ...


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = list(callbacks)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def __getattr__(self, name):
        if name.startswith("on_"):
            def call(*args, **kwargs):
                for c in self.callbacks:
                    getattr(c, name)(*args, **kwargs)

            return call
        raise AttributeError(name)


class ProgBarLogger(Callback):
    """Prints per-epoch progress with loss/metrics and throughput."""

    def __init__(self, log_freq: int = 1, verbose: int = 2):
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = (self.params or {}).get("steps")
        self._t0 = time.time()
        if self.verbose:
            print(f"Epoch {epoch + 1}/{self.params.get('epochs', '?')}")

    def _fmt(self, logs):
        items = []
        for k, v in (logs or {}).items():
            if isinstance(v, (numbers.Number, np.floating)):
                items.append(f"{k}: {float(v):.4f}")
            elif isinstance(v, (list, np.ndarray)) and np.size(v) == 1:
                items.append(f"{k}: {float(np.ravel(v)[0]):.4f}")
        return " - ".join(items)

    def on_train_batch_end(self, step, logs=None):
        if self.verbose > 1 and step % self.log_freq == 0:
            print(f"step {step}: {self._fmt(logs)}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._t0
            print(f"epoch {epoch + 1} done in {dt:.1f}s - {self._fmt(logs)}")

    def on_eval_end(self, logs=None):
        if self.verbose:
            print(f"Eval - {self._fmt(logs)}")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq: int = 1, save_dir: str | None = None):
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        self.by_step, self.by_epoch = by_step, by_epoch

    def _sched(self):
        opt = self.model._optimizer
        lr = getattr(opt, "_learning_rate", None)
        from ..optimizer.lr import LRScheduler as Sched

        return lr if isinstance(lr, Sched) else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self.stopped_epoch = 0
        self.wait = 0
        self.best = None
        self.stop_training = False
        self.save_dir = None  # filled from fit(save_dir=...) via set_params

    def set_params(self, params):
        super().set_params(params)
        if isinstance(params, dict) and params.get("save_dir"):
            self.save_dir = params["save_dir"]

    def _better(self, cur, best):
        if best is None:
            return True
        if self.mode == "min":
            return cur < best - self.min_delta
        return cur > best + self.min_delta

    def on_eval_end(self, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        cur = float(np.ravel(cur)[0]) if not isinstance(cur, numbers.Number) else float(cur)
        if self._better(cur, self.best):
            self.best = cur
            self.wait = 0
            if self.save_best_model and self.save_dir is not None:
                self.model.save(os.path.join(self.save_dir, "best_model"))
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stop_training = True
                self.model.stop_training = True
