"""paddle_tpu.hapi (parity: python/paddle/hapi/)."""
from . import callbacks
from .callbacks import (Callback, EarlyStopping, LRScheduler,
                        ModelCheckpoint, ProgBarLogger, ReduceLROnPlateau,
                        VisualDL, WandbCallback)
from .model import Model
from .model_summary import flops, summary

__all__ = [
    "callbacks",
    "Callback",
    "EarlyStopping",
    "LRScheduler",
    "ModelCheckpoint",
    "ProgBarLogger",
    "Model",
    "summary",
    "flops",
]
