"""paddle.nn parity namespace."""
from . import functional, initializer, utils
from .clip import (
    ClipGradByGlobalNorm,
    ClipGradByNorm,
    ClipGradByValue,
)
from .layer.activation import *  # noqa: F401,F403
from .layer.common import *  # noqa: F401,F403
from .layer.container import LayerDict, LayerList, ParameterList, Sequential
from .layer.conv import (
    Conv1D,
    Conv1DTranspose,
    Conv2D,
    Conv2DTranspose,
    Conv3D,
    Conv3DTranspose,
)
from .layer.layers import Layer
from .layer.loss import *  # noqa: F401,F403
from .layer.norm import (
    BatchNorm,
    BatchNorm1D,
    BatchNorm2D,
    BatchNorm3D,
    GroupNorm,
    InstanceNorm1D,
    InstanceNorm2D,
    InstanceNorm3D,
    LayerNorm,
    LocalResponseNorm,
    RMSNorm,
    SpectralNorm,
    SyncBatchNorm,
)
from .layer.pooling import *  # noqa: F401,F403
from .layer.rnn import (
    GRU,
    LSTM,
    RNN,
    BiRNN,
    GRUCell,
    LSTMCell,
    RNNCellBase,
    SimpleRNN,
    SimpleRNNCell,
)
from .decode import BeamSearchDecoder, dynamic_decode
from .layer.transformer import (
    MultiHeadAttention,
    Transformer,
    TransformerDecoder,
    TransformerDecoderLayer,
    TransformerEncoder,
    TransformerEncoderLayer,
)

F = functional
from . import quant  # noqa: F401
