"""Weight initializers.

Parity: python/paddle/nn/initializer/ (Constant, Normal, TruncatedNormal,
Uniform, XavierNormal/Uniform, KaimingNormal/Uniform, Assign, Orthogonal,
Dirac, calculate_gain). Each initializer is a callable
``(shape, dtype) -> jax array``.
"""
from __future__ import annotations

import math

import numpy as np

import jax
import jax.numpy as jnp

from ...framework import dtype as dtype_mod
from ...framework.random import default_generator


def calculate_gain(nonlinearity: str, param=None) -> float:
    recommended = {
        "sigmoid": 1.0,
        "linear": 1.0,
        "conv1d": 1.0,
        "conv2d": 1.0,
        "conv3d": 1.0,
        "tanh": 5.0 / 3,
        "relu": math.sqrt(2.0),
        "leaky_relu": math.sqrt(2.0 / (1 + (param if param is not None else 0.01) ** 2)),
        "selu": 3.0 / 4,
    }
    if nonlinearity not in recommended:
        raise ValueError(f"unsupported nonlinearity: {nonlinearity}")
    return recommended[nonlinearity]


def _fan_in_out(shape):
    shape = list(shape)
    if len(shape) < 2:
        fan_in = fan_out = shape[0] if shape else 1
    else:
        receptive = int(np.prod(shape[2:])) if len(shape) > 2 else 1
        fan_in = shape[0] * receptive if len(shape) > 2 else shape[0]
        fan_out = shape[1] * receptive if len(shape) > 2 else shape[1]
        if len(shape) > 2:
            # conv weights in paddle are [out_c, in_c, *k]
            fan_in = shape[1] * receptive
            fan_out = shape[0] * receptive
    return fan_in, fan_out


class Initializer:
    def __call__(self, shape, dtype="float32"):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value: float = 0.0):
        self.value = value

    def __call__(self, shape, dtype="float32"):
        return jnp.full(list(shape), self.value, dtype_mod.to_jax_dtype(dtype))


class Normal(Initializer):
    def __init__(self, mean: float = 0.0, std: float = 1.0, name=None):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype="float32"):
        key = default_generator.next_key()
        return (
            jax.random.normal(key, list(shape), dtype_mod.to_jax_dtype(dtype)) * self.std
            + self.mean
        )


class TruncatedNormal(Initializer):
    def __init__(self, mean: float = 0.0, std: float = 1.0, a: float = -2.0, b: float = 2.0, name=None):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype="float32"):
        key = default_generator.next_key()
        return (
            jax.random.truncated_normal(
                key, self.a, self.b, list(shape), dtype_mod.to_jax_dtype(dtype)
            )
            * self.std
            + self.mean
        )


class Uniform(Initializer):
    def __init__(self, low: float = -1.0, high: float = 1.0, name=None):
        self.low, self.high = low, high

    def __call__(self, shape, dtype="float32"):
        key = default_generator.next_key()
        return jax.random.uniform(
            key, list(shape), dtype_mod.to_jax_dtype(dtype), self.low, self.high
        )


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain: float = 1.0, name=None):
        self._fan_in, self._fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype="float32"):
        fan_in, fan_out = _fan_in_out(shape)
        fan_in = self._fan_in if self._fan_in is not None else fan_in
        fan_out = self._fan_out if self._fan_out is not None else fan_out
        std = self.gain * math.sqrt(2.0 / (fan_in + fan_out))
        key = default_generator.next_key()
        return jax.random.normal(key, list(shape), dtype_mod.to_jax_dtype(dtype)) * std


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain: float = 1.0, name=None):
        self._fan_in, self._fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype="float32"):
        fan_in, fan_out = _fan_in_out(shape)
        fan_in = self._fan_in if self._fan_in is not None else fan_in
        fan_out = self._fan_out if self._fan_out is not None else fan_out
        limit = self.gain * math.sqrt(6.0 / (fan_in + fan_out))
        key = default_generator.next_key()
        return jax.random.uniform(
            key, list(shape), dtype_mod.to_jax_dtype(dtype), -limit, limit
        )


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope: float = 0.0, nonlinearity: str = "relu"):
        self._fan_in, self.negative_slope, self.nonlinearity = fan_in, negative_slope, nonlinearity

    def __call__(self, shape, dtype="float32"):
        fan_in, _ = _fan_in_out(shape)
        fan_in = self._fan_in if self._fan_in is not None else fan_in
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fan_in)
        key = default_generator.next_key()
        return jax.random.normal(key, list(shape), dtype_mod.to_jax_dtype(dtype)) * std


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope: float = 0.0, nonlinearity: str = "relu"):
        self._fan_in, self.negative_slope, self.nonlinearity = fan_in, negative_slope, nonlinearity

    def __call__(self, shape, dtype="float32"):
        fan_in, _ = _fan_in_out(shape)
        fan_in = self._fan_in if self._fan_in is not None else fan_in
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fan_in)
        key = default_generator.next_key()
        return jax.random.uniform(
            key, list(shape), dtype_mod.to_jax_dtype(dtype), -limit, limit
        )


class Assign(Initializer):
    def __init__(self, value, name=None):
        self.value = value

    def __call__(self, shape, dtype="float32"):
        from ...tensor.tensor import Tensor

        v = self.value
        if isinstance(v, Tensor):
            v = v.numpy()
        arr = np.asarray(v).astype(dtype_mod.to_jax_dtype(dtype))
        if list(arr.shape) != list(shape):
            arr = arr.reshape(list(shape))
        return jnp.asarray(arr)


class Orthogonal(Initializer):
    def __init__(self, gain: float = 1.0, name=None):
        self.gain = gain

    def __call__(self, shape, dtype="float32"):
        key = default_generator.next_key()
        shape = list(shape)
        rows, cols = shape[0], int(np.prod(shape[1:]))
        mat = jax.random.normal(key, (max(rows, cols), min(rows, cols)))
        q, r = jnp.linalg.qr(mat)
        q = q * jnp.sign(jnp.diagonal(r))
        if rows < cols:
            q = q.T
        return (self.gain * q[:rows, :cols]).reshape(shape).astype(dtype_mod.to_jax_dtype(dtype))


class Dirac(Initializer):
    def __init__(self, groups: int = 1, name=None):
        self.groups = groups

    def __call__(self, shape, dtype="float32"):
        out = np.zeros(shape, dtype_mod.to_jax_dtype(dtype))
        out_c, in_c = shape[0], shape[1]
        mins = min(out_c // self.groups, in_c)
        centers = [s // 2 for s in shape[2:]]
        for g in range(self.groups):
            for i in range(mins):
                idx = (g * (out_c // self.groups) + i, i, *centers)
                out[idx] = 1.0
        return jnp.asarray(out)


# functional-style aliases paddle exposes
constant_ = Constant
normal_ = Normal
uniform_ = Uniform
xavier_normal_ = XavierNormal
xavier_uniform_ = XavierUniform
kaiming_normal_ = KaimingNormal
kaiming_uniform_ = KaimingUniform
