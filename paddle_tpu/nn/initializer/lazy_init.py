"""Lazy parameter initialization (reference nn/initializer/lazy_init.py:18).

``with LazyGuard(): model = Net()`` builds the module tree WITHOUT allocating
parameter values; each Parameter carries its initializer thunk and an abstract
``jax.ShapeDtypeStruct`` placeholder (shape/dtype are queryable, data is not).
``param.initialize()`` materializes one parameter; ``materialize(layer)`` does
the whole tree. The TPU-native purpose matches the reference's: build a
multi-billion-parameter model cheaply, decide placement/sharding, THEN allocate
— here the natural follow-up is initializing directly into a NamedSharding.
"""
from __future__ import annotations

import jax


class _LazyState:
    active = False


def in_lazy_mode() -> bool:
    return _LazyState.active


class LazyGuard:
    """Context manager entering lazy-init mode (reference lazy_init.py:93)."""

    def __enter__(self):
        self._prev = _LazyState.active
        _LazyState.active = True
        return self

    def __exit__(self, *exc):
        _LazyState.active = self._prev
        return False


def make_lazy_data(init, shape, dtype):
    """The placeholder a lazily-created Parameter holds: an abstract aval.

    Shape/dtype/size queries work; any compute on it raises, which is exactly
    the reference's "used an uninitialized lazy parameter" failure mode.
    """
    from ...framework import dtype as dtype_mod

    return jax.ShapeDtypeStruct(tuple(int(s) for s in shape),
                                dtype_mod.to_jax_dtype(dtype))


def materialize(layer_or_param, device=None):
    """Initialize every lazy parameter under ``layer_or_param`` in place."""
    from ...tensor.tensor import Parameter

    if isinstance(layer_or_param, Parameter):
        layer_or_param.initialize()
        return layer_or_param
    for p in layer_or_param.parameters():
        if getattr(p, "_lazy_init", None) is not None:
            p.initialize()
    return layer_or_param
