"""paddle.nn.quant parity: weight-only quantization ops.

Reference: phi/kernels/gpu/weight_quantize_kernel.cu /
weight_only_linear_kernel.cu (cutlass int8/int4 weight-only GEMM). TPU
stance (round 10): storage is the quantized tensor + scales, and the
matmul runs the FUSED Pallas weight-only GEMM
(``ops.pallas.quant_matmul``) — weights stay int8/int4 in HBM and
dequantize tile-by-tile inside the kernel on the way into the MXU, so the
2-4x weight-memory/HBM-bandwidth saving survives all the way through the
matmul (the reference's int8 tensor-core path maps onto the MXU's bf16
pass with in-kernel widening). The jnp dequantize-then-matmul path is
kept as the numerical oracle and the non-TPU fallback.

int4 values are NIBBLE-PACKED two per byte (``pack_int4`` split-half
layout: byte ``i`` holds row ``i`` low-nibble, row ``K/2 + i``
high-nibble) — the memory saving is a true 4x over bf16. ``group_size >
0`` selects per-group scales ``[K/group_size, N]`` along the in-dim
(finer quantization for serving accuracy); the default ``-1`` keeps the
reference's per-output-channel scales.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...autograd.engine import apply_op

__all__ = ["weight_quantize", "weight_dequantize", "weight_only_linear",
           "quant_matmul", "grouped_matmul"]


def _qmax(algo: str) -> float:
    return 127.0 if algo in ("weight_only_int8", "llm.int8") else 7.0


def _is_int4(algo: str) -> bool:
    return algo == "weight_only_int4"


def _weight_quantize_fn(w, qmax, int4, group_size):
    """The pure quantizer body (jnp in, jnp out) — ONE spelling shared by
    the eager op below and the serving converter's ``jax.vmap`` over
    layer stacks (inference/quantize.py)."""
    from ...ops.pallas.quant_matmul import pack_int4

    k = w.shape[0]
    if group_size in (-1, None, 0):
        wf = w.astype(jnp.float32)
        absmax = jnp.max(jnp.abs(wf), axis=0)
        scale = jnp.maximum(absmax, 1e-8) / qmax
        q = jnp.clip(jnp.round(wf / scale[None, :]),
                     -qmax, qmax).astype(jnp.int8)
        s_out = scale.astype(w.dtype)
    else:
        if k % group_size:
            raise ValueError(
                f"in-dim {k} not divisible by group_size {group_size}")
        g = k // group_size
        wf = w.astype(jnp.float32).reshape(g, group_size, -1)
        absmax = jnp.max(jnp.abs(wf), axis=1)            # [g, out]
        scale = jnp.maximum(absmax, 1e-8) / qmax
        q = jnp.clip(jnp.round(wf / scale[:, None, :]), -qmax, qmax)
        q = q.reshape(k, -1).astype(jnp.int8)
        s_out = scale.astype(w.dtype)
    if int4:
        q = pack_int4(q)
    return q, s_out


def weight_quantize(x, algo="weight_only_int8", arch=None, group_size=-1):
    """Symmetric quantization of a ``[in, out]`` weight.

    ``group_size = -1``: per-output-channel scales ``[out]``;
    ``group_size > 0``: per-group scales ``[in / group_size, out]`` (the
    in-dim must divide). int8 returns ``(int8 [in, out], scales)``; int4
    returns (packed int8 ``[in/2, out]`` — two nibbles per byte, see
    ``ops.pallas.quant_matmul.pack_int4`` — and the same scale layout).
    """
    qmax = _qmax(algo)
    int4 = _is_int4(algo)

    def fn(w):
        return _weight_quantize_fn(w, qmax, int4, group_size)

    return apply_op("weight_quantize", fn, x)


def weight_dequantize(x, scale, algo="weight_only_int8", out_dtype=None):
    """Materialize the fp weight back from (quantized, scales) — unpacks
    int4 nibbles first. scales ``[out]`` (per-channel) or ``[groups,
    out]`` (per-group); result in ``out_dtype`` (default: the scales'
    dtype)."""

    def fn(q, s):
        from ...ops.pallas.quant_matmul import unpack_int4

        if _is_int4(algo):
            q = unpack_int4(q)
        k = q.shape[0]
        s2 = s.reshape(1, -1) if s.ndim == 1 else s
        out = q.astype(jnp.float32) * jnp.repeat(
            s2.astype(jnp.float32), k // s2.shape[0], axis=0)
        return out.astype(s.dtype if out_dtype is None else out_dtype)

    return apply_op("weight_dequantize", fn, x, scale)


def weight_only_linear(x, weight, bias=None, weight_scale=None,
                       weight_dtype="int8", arch=None, group_size=-1,
                       use_kernel=None):
    """y = x @ dequant(weight) + bias (reference: weight_only_linear op),
    running the FUSED weight-only Pallas GEMM — the weight stays int8
    (``[in, out]``) or nibble-packed int4 (``[in/2, out]``) in HBM.
    ``use_kernel``: None = kernel on TPU / jnp oracle elsewhere; True
    forces the kernel (interpret mode — CPU tests); False the oracle."""

    def fn(v, q, s, b):
        from ...ops.pallas.quant_matmul import quant_matmul as _qmm

        return _qmm(v, q, s, bias=b, use_kernel=use_kernel)

    return apply_op("weight_only_linear", fn, x, weight, weight_scale, bias)


def quant_matmul(x, qweight, scales, bias=None, use_kernel=None):
    """The fused weight-only GEMM as a standalone op: ``x @
    dequant(qweight) + bias`` with int8/packed-int4 ``qweight`` and
    per-channel (``[out]``) or per-group (``[groups, out]``) scales. See
    ``ops.pallas.quant_matmul.quant_matmul``."""

    def fn(v, q, s, b):
        from ...ops.pallas.quant_matmul import quant_matmul as _qmm

        return _qmm(v, q, s, bias=b, use_kernel=use_kernel)

    return apply_op("quant_matmul", fn, x, qweight, scales, bias)


def grouped_matmul(x, weights, group_offsets, scales=None, use_kernel=None):
    """Ragged grouped GEMM (round-25 MoE expert path): ``out[i] = x[i] @
    dequant(weights)[g(i)]`` where ``g(i)`` is the group owning row ``i``.
    ``x [M, K]`` rows pre-sorted by group, ``weights [E, K, N]`` fp /
    int8 / nibble-packed int4 expert stack, ``group_offsets [E+1]``
    prefix sum (empty groups allowed), ``scales`` per-expert ``[E, N]``
    or ``[E, groups, N]`` iff quantized. See
    ``ops.pallas.grouped_matmul.grouped_matmul``."""

    def fn(v, w, offs, s):
        from ...ops.pallas.grouped_matmul import grouped_matmul as _gmm

        return _gmm(v, w, offs, scales=s, use_kernel=use_kernel)

    return apply_op("grouped_matmul", fn, x, weights, group_offsets, scales)
