"""paddle.nn.quant parity: weight-only quantization ops.

Reference: phi/kernels/gpu/weight_quantize_kernel.cu /
weight_only_linear_kernel.cu (cutlass int8/int4 weight-only GEMM). TPU
stance: storage is the quantized int8 tensor + per-channel scales; the
matmul DEQUANTIZES to the activation dtype and rides the MXU — the win kept
is the 2-4x weight-memory/HBM-bandwidth saving, which is what weight-only
quant buys on accelerators (the reference's int8 tensor cores are the MXU's
bf16 pass here). int4 values are stored one-per-int8 byte (no packing; XLA
has no sub-byte dtype) — memory saving is 2x, not 4x, documented honestly.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...autograd.engine import apply_op

__all__ = ["weight_quantize", "weight_dequantize", "weight_only_linear"]


def weight_quantize(x, algo="weight_only_int8", arch=None, group_size=-1):
    """Per-output-channel symmetric quantization of a [in, out] weight.
    Returns (quantized int8 [in, out], scale [out] in the input dtype)."""
    qmax = 127.0 if algo in ("weight_only_int8", "llm.int8") else 7.0

    def fn(w):
        absmax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=0)
        scale = absmax / qmax
        q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale[None, :]),
                     -qmax, qmax).astype(jnp.int8)
        return q, scale.astype(w.dtype)

    return apply_op("weight_quantize", fn, x)


def weight_dequantize(x, scale, algo="weight_only_int8", out_dtype=None):
    def fn(q, s):
        out = q.astype(jnp.float32) * s[None, :].astype(jnp.float32)
        return out.astype(s.dtype)

    return apply_op("weight_dequantize", fn, x, scale)


def weight_only_linear(x, weight, bias=None, weight_scale=None,
                       weight_dtype="int8", arch=None, group_size=-1):
    """y = x @ dequant(weight) + bias (reference: weight_only_linear op).
    weight int8 [in, out], weight_scale [out]."""

    def fn(v, q, s, b):
        w = q.astype(v.dtype) * s[None, :].astype(v.dtype)
        y = v @ w
        if b is not None:
            y = y + b
        return y

    return apply_op("weight_only_linear", fn, x, weight, weight_scale, bias)
