"""Recurrent layers via lax.scan (XLA-friendly sequential scan).

Parity: python/paddle/nn/layer/rnn.py (SimpleRNN/LSTM/GRU + cells). The whole
sequence loop is ONE scan inside ONE autograd op, so jit sees structured
control flow (no Python loop unrolling).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ...autograd.engine import apply_op
from ...tensor.tensor import Tensor
from ..initializer import Uniform
from .layers import Layer


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype=None, init_value=0.0, batch_dim_idx=0):
        b = batch_ref.shape[batch_dim_idx]
        from ...tensor.creation import full

        return full([b, self.hidden_size], init_value, dtype=dtype or "float32")


def _rnn_params(layer, input_size, hidden_size, gates):
    k = 1.0 / np.sqrt(hidden_size)
    init = Uniform(-k, k)
    layer.weight_ih = layer.create_parameter([gates * hidden_size, input_size], default_initializer=init)
    layer.weight_hh = layer.create_parameter([gates * hidden_size, hidden_size], default_initializer=init)
    layer.bias_ih = layer.create_parameter([gates * hidden_size], is_bias=True, default_initializer=init)
    layer.bias_hh = layer.create_parameter([gates * hidden_size], is_bias=True, default_initializer=init)


def _lstm_step(x_t, h, c, w_ih, w_hh, b_ih, b_hh):
    gates = x_t @ w_ih.T + h @ w_hh.T + b_ih + b_hh
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
    g = jnp.tanh(g)
    c_new = f * c + i * g
    h_new = o * jnp.tanh(c_new)
    return h_new, c_new


def _gru_step(x_t, h, w_ih, w_hh, b_ih, b_hh):
    gi = x_t @ w_ih.T + b_ih
    gh = h @ w_hh.T + b_hh
    i_r, i_z, i_n = jnp.split(gi, 3, axis=-1)
    h_r, h_z, h_n = jnp.split(gh, 3, axis=-1)
    r = jax.nn.sigmoid(i_r + h_r)
    z = jax.nn.sigmoid(i_z + h_z)
    n = jnp.tanh(i_n + r * h_n)
    return (1 - z) * n + z * h


def _simple_step(x_t, h, w_ih, w_hh, b_ih, b_hh, activation):
    out = x_t @ w_ih.T + h @ w_hh.T + b_ih + b_hh
    return jnp.tanh(out) if activation == "tanh" else jax.nn.relu(out)


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.hidden_size = hidden_size
        _rnn_params(self, input_size, hidden_size, 4)

    def forward(self, inputs, states=None):
        if states is None:
            states = (self.get_initial_states(inputs), self.get_initial_states(inputs))
        h, c = states

        def fn(x, hh, cc, w_ih, w_hh, b_ih, b_hh):
            return _lstm_step(x, hh, cc, w_ih, w_hh, b_ih, b_hh)

        h_new, c_new = apply_op(
            "lstm_cell", fn, inputs, h, c, self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh
        )
        return h_new, (h_new, c_new)


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.hidden_size = hidden_size
        _rnn_params(self, input_size, hidden_size, 3)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h_new = apply_op(
            "gru_cell", _gru_step, inputs, states, self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh
        )
        return h_new, h_new


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh", weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.hidden_size = hidden_size
        self.activation = activation
        _rnn_params(self, input_size, hidden_size, 1)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h_new = apply_op(
            "simple_rnn_cell",
            lambda x, h, wi, wh, bi, bh: _simple_step(x, h, wi, wh, bi, bh, self.activation),
            inputs, states, self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh,
        )
        return h_new, h_new


class _RNNBase(Layer):
    """Multi-layer (bi)directional recurrent net; one lax.scan per layer&dir."""

    MODE_GATES = {"LSTM": 4, "GRU": 3, "RNN_TANH": 1, "RNN_RELU": 1}

    def __init__(self, mode, input_size, hidden_size, num_layers=1, direction="forward", time_major=False, dropout=0.0, activation="tanh"):
        super().__init__()
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.bidirect = direction in ("bidirect", "bidirectional")
        num_dirs = 2 if self.bidirect else 1
        gates = self.MODE_GATES[mode]
        k = 1.0 / np.sqrt(hidden_size)
        init = Uniform(-k, k)
        self._all_weights = []
        for layer_i in range(num_layers):
            for d in range(num_dirs):
                in_size = input_size if layer_i == 0 else hidden_size * num_dirs
                suffix = f"_l{layer_i}" + ("_reverse" if d else "")
                w_ih = self.create_parameter([gates * hidden_size, in_size], default_initializer=init)
                w_hh = self.create_parameter([gates * hidden_size, hidden_size], default_initializer=init)
                b_ih = self.create_parameter([gates * hidden_size], is_bias=True, default_initializer=init)
                b_hh = self.create_parameter([gates * hidden_size], is_bias=True, default_initializer=init)
                for n, p in [("weight_ih", w_ih), ("weight_hh", w_hh), ("bias_ih", b_ih), ("bias_hh", b_hh)]:
                    self.add_parameter(n + suffix, p)
                self._all_weights.append((w_ih, w_hh, b_ih, b_hh))

    def forward(self, inputs, initial_states=None, sequence_length=None):
        num_dirs = 2 if self.bidirect else 1
        is_lstm = self.mode == "LSTM"
        batch_axis = 1 if self.time_major else 0
        b = inputs.shape[batch_axis]
        n_states = self.num_layers * num_dirs
        from ...tensor.creation import zeros

        if initial_states is None:
            h0 = zeros([n_states, b, self.hidden_size], dtype=inputs.dtype)
            initial_states = (h0, zeros([n_states, b, self.hidden_size], dtype=inputs.dtype)) if is_lstm else h0

        flat_weights = [w for tup in self._all_weights for w in tup]
        mode = self.mode
        time_major = self.time_major
        num_layers = self.num_layers
        activation = "tanh" if mode != "RNN_RELU" else "relu"

        def fn(x, *rest):
            if is_lstm:
                h0_, c0_ = rest[0], rest[1]
                weights = rest[2:]
            else:
                h0_ = rest[0]
                weights = rest[1:]
            if not time_major:
                x = jnp.swapaxes(x, 0, 1)  # [T, B, F]
            layer_in = x
            final_h, final_c = [], []
            wi = 0
            for li in range(num_layers):
                dir_outs = []
                for d in range(num_dirs):
                    w_ih, w_hh, b_ih, b_hh = weights[4 * wi : 4 * wi + 4]
                    wi += 1
                    idx = li * num_dirs + d
                    h_init = h0_[idx]
                    c_init = c0_[idx] if is_lstm else None
                    seq = jnp.flip(layer_in, 0) if d == 1 else layer_in

                    if is_lstm:
                        def step(carry, x_t, _w=(w_ih, w_hh, b_ih, b_hh)):
                            hh, cc = carry
                            h_new, c_new = _lstm_step(x_t, hh, cc, *_w)
                            return (h_new, c_new), h_new

                        (h_fin, c_fin), outs = jax.lax.scan(step, (h_init, c_init), seq)
                        final_c.append(c_fin)
                    elif mode == "GRU":
                        def step(h, x_t, _w=(w_ih, w_hh, b_ih, b_hh)):
                            h_new = _gru_step(x_t, h, *_w)
                            return h_new, h_new

                        h_fin, outs = jax.lax.scan(step, h_init, seq)
                    else:
                        def step(h, x_t, _w=(w_ih, w_hh, b_ih, b_hh)):
                            h_new = _simple_step(x_t, h, *_w, activation)
                            return h_new, h_new

                        h_fin, outs = jax.lax.scan(step, h_init, seq)
                    if d == 1:
                        outs = jnp.flip(outs, 0)
                    final_h.append(h_fin)
                    dir_outs.append(outs)
                layer_in = jnp.concatenate(dir_outs, axis=-1) if num_dirs == 2 else dir_outs[0]
            out = layer_in if time_major else jnp.swapaxes(layer_in, 0, 1)
            h_stack = jnp.stack(final_h, 0)
            if is_lstm:
                return out, h_stack, jnp.stack(final_c, 0)
            return out, h_stack

        if is_lstm:
            out, h, c = apply_op(
                f"rnn_{mode}", fn, inputs, initial_states[0], initial_states[1], *flat_weights
            )
            return out, (h, c)
        out, h = apply_op(f"rnn_{mode}", fn, inputs, initial_states, *flat_weights)
        return out, h


class LSTM(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward", time_major=False, dropout=0.0, weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__("LSTM", input_size, hidden_size, num_layers, direction, time_major, dropout)


class GRU(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward", time_major=False, dropout=0.0, weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__("GRU", input_size, hidden_size, num_layers, direction, time_major, dropout)


class SimpleRNN(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward", time_major=False, dropout=0.0, activation="tanh", weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__("RNN_TANH" if activation == "tanh" else "RNN_RELU", input_size, hidden_size, num_layers, direction, time_major, dropout)


class RNN(Layer):
    """Generic RNN wrapper running a cell over a sequence (paddle.nn.RNN)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...tensor.manipulation import stack, unbind

        time_axis = 0 if self.time_major else 1
        steps = unbind(inputs, axis=time_axis)
        if self.is_reverse:
            steps = steps[::-1]
        states = initial_states
        outs = []
        for x_t in steps:
            out, states = self.cell(x_t, states)
            outs.append(out)
        if self.is_reverse:
            outs = outs[::-1]
        return stack(outs, axis=time_axis), states


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...tensor.manipulation import concat

        states_fw, states_bw = (initial_states or (None, None))
        out_fw, st_fw = self.rnn_fw(inputs, states_fw)
        out_bw, st_bw = self.rnn_bw(inputs, states_bw)
        return concat([out_fw, out_bw], axis=-1), (st_fw, st_bw)
