"""Norm layers (parity: python/paddle/nn/layer/norm.py)."""
from __future__ import annotations

import numpy as np

from ...tensor.tensor import Tensor
from .. import functional as F
from ..initializer import Constant
from .layers import Layer


class _BatchNormBase(Layer):
    def __init__(
        self,
        num_features,
        momentum=0.9,
        epsilon=1e-05,
        weight_attr=None,
        bias_attr=None,
        data_format="NCHW",
        use_global_stats=None,
        name=None,
    ):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        if weight_attr is not False:
            self.weight = self.create_parameter(
                [num_features], attr=weight_attr, default_initializer=Constant(1.0)
            )
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter([num_features], attr=bias_attr, is_bias=True)
        else:
            self.bias = None
        self.register_buffer("_mean", Tensor(np.zeros(num_features, np.float32)))
        self.register_buffer("_variance", Tensor(np.ones(num_features, np.float32)))

    def forward(self, x):
        return F.batch_norm(
            x,
            self._mean,
            self._variance,
            weight=self.weight,
            bias=self.bias,
            training=self.training,
            momentum=self._momentum,
            epsilon=self._epsilon,
            data_format=self._data_format,
            use_global_stats=self._use_global_stats,
        )

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}, epsilon={self._epsilon}"


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05, weight_attr=None, bias_attr=None, data_format="NCDHW", use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr, "NCHW" if data_format == "NCDHW" else "NHWC", use_global_stats, name)


class SyncBatchNorm(_BatchNormBase):
    """On TPU, XLA's SPMD partitioner makes plain batch_norm sync'd when the
    batch axis is sharded — stats reductions become cross-replica psums
    automatically. So SyncBatchNorm == BatchNorm here (the reference needed a
    dedicated NCCL kernel; reference python/paddle/nn/layer/norm.py
    SyncBatchNorm)."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        for _ in ():
            pass
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-05, weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        if weight_attr is not False:
            self.weight = self.create_parameter(
                self._normalized_shape, attr=weight_attr, default_initializer=Constant(1.0)
            )
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                self._normalized_shape, attr=bias_attr, is_bias=True
            )
        else:
            self.bias = None

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias, self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}, epsilon={self._epsilon}"


class RMSNorm(Layer):
    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            [hidden_size], attr=weight_attr, default_initializer=Constant(1.0)
        )

    def forward(self, x):
        return F.rms_norm(x, self.weight, self._epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-05, weight_attr=None, bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = (
            self.create_parameter([num_channels], attr=weight_attr, default_initializer=Constant(1.0))
            if weight_attr is not False
            else None
        )
        self.bias = (
            self.create_parameter([num_channels], attr=bias_attr, is_bias=True)
            if bias_attr is not False
            else None
        )

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight, self.bias, self._data_format)


class InstanceNorm1D(Layer):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9, weight_attr=None, bias_attr=None, data_format="NCL", name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = (
            self.create_parameter([num_features], attr=weight_attr, default_initializer=Constant(1.0))
            if weight_attr is not False
            else None
        )
        self.bias = (
            self.create_parameter([num_features], attr=bias_attr, is_bias=True)
            if bias_attr is not False
            else None
        )

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias, eps=self._epsilon)


class InstanceNorm2D(InstanceNorm1D):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9, weight_attr=None, bias_attr=None, data_format="NCHW", name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr, bias_attr, data_format, name)


class InstanceNorm3D(InstanceNorm1D):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9, weight_attr=None, bias_attr=None, data_format="NCDHW", name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr, bias_attr, data_format, name)


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=0.0001, beta=0.75, k=1.0, data_format="NCHW", name=None):
        super().__init__()
        self.args = (size, alpha, beta, k, data_format)

    def forward(self, x):
        return F.local_response_norm(x, *self.args)


class SpectralNorm(Layer):
    """Spectral normalization as a LAYER over a weight tensor (reference
    nn/layer/norm.py SpectralNorm / phi spectral_norm kernel): power
    iteration estimates the largest singular value of the weight reshaped
    to [dim, -1]; forward returns weight / sigma. The u/v estimates are
    persistent buffers (reference keeps them as persistable vars)."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 epsilon=None, dtype="float32", name=None):
        if epsilon is not None:  # reference kwarg spelling
            eps = epsilon
        super().__init__()
        import numpy as np

        import jax.numpy as jnp

        self._dim = int(dim)
        self._power_iters = int(power_iters)
        self._eps = float(eps)
        h = int(weight_shape[self._dim])
        w = 1
        for i, s in enumerate(weight_shape):
            if i != self._dim:
                w *= int(s)
        rng = np.random.RandomState(0)
        from ...tensor.tensor import Tensor

        self.weight_u = Tensor(jnp.asarray(
            rng.randn(h).astype("float32")))
        self.weight_v = Tensor(jnp.asarray(
            rng.randn(w).astype("float32")))
        self.register_buffer("weight_u", self.weight_u)
        self.register_buffer("weight_v", self.weight_v)

    def forward(self, weight):
        from ...autograd.engine import apply_op

        dim, iters, eps = self._dim, self._power_iters, self._eps

        def fn(w, u, v):
            import jax
            import jax.numpy as jnp

            perm = (dim,) + tuple(i for i in range(w.ndim) if i != dim)
            m = jnp.transpose(w, perm).reshape(w.shape[dim], -1)

            def it(_, uv):
                u_, v_ = uv
                v_ = m.T @ u_
                v_ = v_ / (jnp.linalg.norm(v_) + eps)
                u_ = m @ v_
                u_ = u_ / (jnp.linalg.norm(u_) + eps)
                return u_, v_

            u_, v_ = jax.lax.fori_loop(0, iters, it, (u, v))
            u_ = jax.lax.stop_gradient(u_)
            v_ = jax.lax.stop_gradient(v_)
            sigma = u_ @ (m @ v_)
            return w / sigma, u_, v_

        out, u_new, v_new = apply_op("spectral_norm", fn, weight,
                                     self.weight_u, self.weight_v)
        # persist the power-iteration state (buffers, not differentiable)
        self.weight_u._data = u_new._data
        self.weight_v._data = v_new._data
        return out
