"""Pooling layers (parity: python/paddle/nn/layer/pooling.py)."""
from __future__ import annotations

from .. import functional as F
from .layers import Layer


def _make(name, fn_name, arg_names):
    def __init__(self, *args, **kwargs):
        Layer.__init__(self)
        merged = dict(zip(arg_names, args))
        merged.update(kwargs)
        merged.pop("name", None)
        self._kwargs = merged

    def forward(self, x):
        return getattr(F, fn_name)(x, **self._kwargs)

    return type(name, (Layer,), {"__init__": __init__, "forward": forward})


MaxPool1D = _make("MaxPool1D", "max_pool1d", ["kernel_size", "stride", "padding", "return_mask", "ceil_mode"])
MaxPool2D = _make("MaxPool2D", "max_pool2d", ["kernel_size", "stride", "padding", "ceil_mode", "return_mask", "data_format"])
MaxPool3D = _make("MaxPool3D", "max_pool3d", ["kernel_size", "stride", "padding", "ceil_mode", "return_mask", "data_format"])
AvgPool1D = _make("AvgPool1D", "avg_pool1d", ["kernel_size", "stride", "padding", "exclusive", "ceil_mode"])
AvgPool2D = _make("AvgPool2D", "avg_pool2d", ["kernel_size", "stride", "padding", "ceil_mode", "exclusive", "divisor_override", "data_format"])
AvgPool3D = _make("AvgPool3D", "avg_pool3d", ["kernel_size", "stride", "padding", "ceil_mode", "exclusive", "divisor_override", "data_format"])
AdaptiveAvgPool1D = _make("AdaptiveAvgPool1D", "adaptive_avg_pool1d", ["output_size"])
AdaptiveAvgPool2D = _make("AdaptiveAvgPool2D", "adaptive_avg_pool2d", ["output_size", "data_format"])
AdaptiveAvgPool3D = _make("AdaptiveAvgPool3D", "adaptive_avg_pool3d", ["output_size", "data_format"])
AdaptiveMaxPool1D = _make("AdaptiveMaxPool1D", "adaptive_max_pool1d", ["output_size", "return_mask"])
AdaptiveMaxPool2D = _make("AdaptiveMaxPool2D", "adaptive_max_pool2d", ["output_size", "return_mask"])
AdaptiveMaxPool3D = _make("AdaptiveMaxPool3D", "adaptive_max_pool3d", ["output_size", "return_mask"])
LPPool1D = _make("LPPool1D", "lp_pool1d", ["norm_type", "kernel_size", "stride", "padding", "ceil_mode", "data_format"])
LPPool2D = _make("LPPool2D", "lp_pool2d", ["norm_type", "kernel_size", "stride", "padding", "ceil_mode", "data_format"])

FractionalMaxPool2D = _make(
    "FractionalMaxPool2D", "fractional_max_pool2d",
    ["output_size", "kernel_size", "random_u", "return_mask"])
FractionalMaxPool3D = _make(
    "FractionalMaxPool3D", "fractional_max_pool3d",
    ["output_size", "kernel_size", "random_u", "return_mask"])


def _make_unpool(cls_name, fn_name, data_format_default):
    import paddle_tpu.nn.functional as F

    class _UnPool(Layer):
        def __init__(self, kernel_size, stride=None, padding=0,
                     data_format=data_format_default, output_size=None,
                     name=None):
            super().__init__()
            self._args = (kernel_size, stride, padding, data_format,
                          output_size)

        def forward(self, x, indices):
            k, s, p, df, out = self._args
            return getattr(F, fn_name)(x, indices, k, stride=s, padding=p,
                                       data_format=df, output_size=out)

    _UnPool.__name__ = cls_name
    return _UnPool


MaxUnPool1D = _make_unpool("MaxUnPool1D", "max_unpool1d", "NCL")
MaxUnPool2D = _make_unpool("MaxUnPool2D", "max_unpool2d", "NCHW")
MaxUnPool3D = _make_unpool("MaxUnPool3D", "max_unpool3d", "NCDHW")
