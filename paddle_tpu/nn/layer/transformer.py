"""Transformer layers.

Parity: python/paddle/nn/layer/transformer.py (MultiHeadAttention,
TransformerEncoder/Decoder, Transformer). Attention routes through
scaled_dot_product_attention so the Pallas flash kernel is used on TPU.
"""
from __future__ import annotations

import copy

from ...tensor.manipulation import concat, reshape, transpose
from .. import functional as F
from .activation import ReLU
from .common import Dropout, Linear
from .container import LayerList
from .layers import Layer
from .norm import LayerNorm


def _convert_attention_mask(attn_mask, dtype):
    if attn_mask is None:
        return None
    if attn_mask.dtype.is_bool:
        # True = keep, False = mask (paddle convention)
        from ...tensor.search import where
        from ...tensor.creation import full_like, zeros_like

        big_neg = full_like(attn_mask.astype(dtype), -1e9)
        return where(attn_mask, zeros_like(big_neg), big_neg)
    return attn_mask.astype(dtype)


class MultiHeadAttention(Layer):
    Cache = tuple
    StaticCache = tuple

    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None, vdim=None, need_weights=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.dropout = dropout
        self.need_weights = need_weights
        kdim = kdim or embed_dim
        vdim = vdim or embed_dim
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        key = query if key is None else key
        value = query if value is None else value
        b, s_q = query.shape[0], query.shape[1]
        q = self.q_proj(query)
        k = self.k_proj(key)
        v = self.v_proj(value)
        if cache is not None:
            k_cache, v_cache = cache
            k_new = reshape(k, [b, -1, self.num_heads, self.head_dim])
            v_new = reshape(v, [b, -1, self.num_heads, self.head_dim])
            k4 = concat([k_cache, k_new], axis=1)
            v4 = concat([v_cache, v_new], axis=1)
            new_cache = (k4, v4)
        else:
            k4 = reshape(k, [b, -1, self.num_heads, self.head_dim])
            v4 = reshape(v, [b, -1, self.num_heads, self.head_dim])
            new_cache = None
        q4 = reshape(q, [b, s_q, self.num_heads, self.head_dim])
        mask = _convert_attention_mask(attn_mask, q.dtype)
        out = F.scaled_dot_product_attention(
            q4, k4, v4, attn_mask=mask, dropout_p=self.dropout, training=self.training
        )
        out = reshape(out, [b, s_q, self.embed_dim])
        out = self.out_proj(out)
        outs = [out]
        if self.need_weights:
            outs.append(None)
        if cache is not None:
            outs.append(new_cache)
        return out if len(outs) == 1 else tuple(outs)

    def gen_cache(self, key, value=None, type=None):
        b = key.shape[0]
        from ...tensor.creation import zeros

        if value is None:
            k = zeros([b, 0, self.num_heads, self.head_dim], dtype=key.dtype)
            v = zeros([b, 0, self.num_heads, self.head_dim], dtype=key.dtype)
            return (k, v)
        return (key, value)


class TransformerEncoderLayer(Layer):
    def __init__(
        self,
        d_model,
        nhead,
        dim_feedforward,
        dropout=0.1,
        activation="relu",
        attn_dropout=None,
        act_dropout=None,
        normalize_before=False,
        weight_attr=None,
        bias_attr=None,
        layer_norm_eps=1e-5,
    ):
        super().__init__()
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(
            d_model, nhead, dropout=attn_dropout if attn_dropout is not None else dropout,
            weight_attr=weight_attr, bias_attr=bias_attr,
        )
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.dropout = Dropout(act_dropout if act_dropout is not None else dropout)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.norm2 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.activation = getattr(F, activation)

    def forward(self, src, src_mask=None, cache=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        if cache is None:
            src = self.self_attn(src, src, src, src_mask)
        else:
            src, cache = self.self_attn(src, src, src, src_mask, cache)
        src = residual + self.dropout1(src)
        if not self.normalize_before:
            src = self.norm1(src)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        src = self.linear2(self.dropout(self.activation(self.linear1(src))))
        src = residual + self.dropout2(src)
        if not self.normalize_before:
            src = self.norm2(src)
        return src if cache is None else (src, cache)

    def gen_cache(self, src):
        return self.self_attn.gen_cache(src)


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        self.layers = LayerList(
            [encoder_layer] + [copy.deepcopy(encoder_layer) for _ in range(num_layers - 1)]
        )
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None, cache=None):
        output = src
        new_caches = []
        for i, layer in enumerate(self.layers):
            if cache is None:
                output = layer(output, src_mask)
            else:
                output, c = layer(output, src_mask, cache[i])
                new_caches.append(c)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, src):
        return [layer.gen_cache(src) for layer in self.layers]


class TransformerDecoderLayer(Layer):
    def __init__(
        self,
        d_model,
        nhead,
        dim_feedforward,
        dropout=0.1,
        activation="relu",
        attn_dropout=None,
        act_dropout=None,
        normalize_before=False,
        weight_attr=None,
        bias_attr=None,
        layer_norm_eps=1e-5,
    ):
        super().__init__()
        self.normalize_before = normalize_before
        attn_drop = attn_dropout if attn_dropout is not None else dropout
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_drop, weight_attr=weight_attr, bias_attr=bias_attr)
        self.cross_attn = MultiHeadAttention(d_model, nhead, attn_drop, weight_attr=weight_attr, bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.dropout = Dropout(act_dropout if act_dropout is not None else dropout)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.norm2 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.norm3 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout3 = Dropout(dropout)
        self.activation = getattr(F, activation)

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        if cache is None:
            tgt = self.self_attn(tgt, tgt, tgt, tgt_mask)
        else:
            tgt, new_cache = self.self_attn(tgt, tgt, tgt, tgt_mask, cache[0])
        tgt = residual + self.dropout1(tgt)
        if not self.normalize_before:
            tgt = self.norm1(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        tgt = self.cross_attn(tgt, memory, memory, memory_mask)
        tgt = residual + self.dropout2(tgt)
        if not self.normalize_before:
            tgt = self.norm2(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        tgt = self.linear2(self.dropout(self.activation(self.linear1(tgt))))
        tgt = residual + self.dropout3(tgt)
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        return tgt if cache is None else (tgt, (new_cache,))

    def gen_cache(self, memory):
        return (self.self_attn.gen_cache(memory),)


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        self.layers = LayerList(
            [decoder_layer] + [copy.deepcopy(decoder_layer) for _ in range(num_layers - 1)]
        )
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        output = tgt
        new_caches = []
        for i, layer in enumerate(self.layers):
            if cache is None:
                output = layer(output, memory, tgt_mask, memory_mask)
            else:
                output, c = layer(output, memory, tgt_mask, memory_mask, cache[i])
                new_caches.append(c)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, memory, do_zip=False):
        return [layer.gen_cache(memory) for layer in self.layers]


class Transformer(Layer):
    def __init__(
        self,
        d_model=512,
        nhead=8,
        num_encoder_layers=6,
        num_decoder_layers=6,
        dim_feedforward=2048,
        dropout=0.1,
        activation="relu",
        attn_dropout=None,
        act_dropout=None,
        normalize_before=False,
        weight_attr=None,
        bias_attr=None,
        custom_encoder=None,
        custom_decoder=None,
    ):
        super().__init__()
        if custom_encoder is not None:
            self.encoder = custom_encoder
        else:
            enc_layer = TransformerEncoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr, bias_attr,
            )
            self.encoder = TransformerEncoder(
                enc_layer, num_encoder_layers, LayerNorm(d_model) if normalize_before else None
            )
        if custom_decoder is not None:
            self.decoder = custom_decoder
        else:
            dec_layer = TransformerDecoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr, bias_attr,
            )
            self.decoder = TransformerDecoder(
                dec_layer, num_decoder_layers, LayerNorm(d_model) if normalize_before else None
            )
        self.d_model = d_model
        self.nhead = nhead

    def forward(self, src, tgt, src_mask=None, tgt_mask=None, memory_mask=None):
        memory = self.encoder(src, src_mask)
        return self.decoder(tgt, memory, tgt_mask, memory_mask)

    @staticmethod
    def generate_square_subsequent_mask(length):
        import numpy as np

        from ...tensor.tensor import Tensor

        mask = np.triu(np.full((length, length), -np.inf, np.float32), 1)
        return Tensor(mask)
