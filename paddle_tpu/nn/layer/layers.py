"""Layer: the module base class.

Parity: paddle.nn.Layer (reference: python/paddle/nn/layer/layers.py:334 —
sublayers/parameters registration, hooks, state_dict, train/eval, apply, to).
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Callable, Iterator

import numpy as np

from ...framework import dtype as dtype_mod
from ...tensor.tensor import Parameter, Tensor


class HookRemoveHelper:
    def __init__(self, hooks: dict, hook_id: int):
        self._hooks = hooks
        self._hook_id = hook_id

    def remove(self):
        self._hooks.pop(self._hook_id, None)


class Layer:
    def __init__(self, name_scope: str | None = None, dtype: str = "float32"):
        self.training = True
        self._dtype = dtype
        self._parameters: OrderedDict[str, Parameter] = OrderedDict()
        self._sub_layers: OrderedDict[str, Layer] = OrderedDict()
        self._buffers: OrderedDict[str, Tensor] = OrderedDict()
        self._non_persistable_buffer_names: set[str] = set()
        self._forward_pre_hooks: OrderedDict[int, Callable] = OrderedDict()
        self._forward_post_hooks: OrderedDict[int, Callable] = OrderedDict()
        self._hook_counter = 0
        self._name_scope = name_scope or self.__class__.__name__.lower()
        self._casted_by_pure_fp16 = False

    # --- registration ---
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning parameters")
            for store in (layers, buffers):
                if store is not None:
                    store.pop(name, None)
            params[name] = value
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ before assigning sublayers")
            for store in (params, buffers):
                if store is not None:
                    store.pop(name, None)
            layers[name] = value
        elif isinstance(value, Tensor) and buffers is not None and name in buffers:
            buffers[name] = value
        else:
            if params is not None:
                params.pop(name, None)
            if layers is not None:
                layers.pop(name, None)
            if buffers is not None and not isinstance(value, Tensor):
                buffers.pop(name, None)
            object.__setattr__(self, name, value)
            return
        # registered containers hold the value; shadow in __dict__ is removed
        self.__dict__.pop(name, None)

    def __getattr__(self, name):
        for store_name in ("_parameters", "_sub_layers", "_buffers"):
            store = self.__dict__.get(store_name)
            if store is not None and name in store:
                return store[name]
        raise AttributeError(
            f"'{self.__class__.__name__}' object has no attribute '{name}'"
        )

    def __delattr__(self, name):
        for store_name in ("_parameters", "_sub_layers", "_buffers"):
            store = self.__dict__.get(store_name)
            if store is not None and name in store:
                del store[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        return list(super().__dir__()) + list(self._parameters) + list(self._sub_layers) + list(self._buffers)

    # --- creation helpers (create_parameter parity) ---
    def create_parameter(
        self,
        shape,
        attr=None,
        dtype=None,
        is_bias: bool = False,
        default_initializer=None,
    ) -> Parameter:
        from ..initializer import Constant, XavierUniform

        dtype = dtype or self._dtype
        init = default_initializer
        name = None
        learning_rate = 1.0
        if attr is not None and attr is not False:
            from ...framework.param_attr import ParamAttr

            if isinstance(attr, ParamAttr):
                init = attr.initializer or init
                name = attr.name
                learning_rate = attr.learning_rate
            elif callable(attr):
                init = attr
        if init is None:
            init = Constant(0.0) if is_bias else XavierUniform()
        from ..initializer import lazy_init

        if lazy_init.in_lazy_mode():
            # LazyGuard: no allocation — the Parameter holds an abstract aval
            # and its initializer thunk until .initialize()
            p = Parameter(lazy_init.make_lazy_data(init, shape, dtype),
                          dtype=dtype, name=name)
            p._lazy_init = (init, list(shape), dtype)
        else:
            p = Parameter(init(shape, dtype), dtype=dtype, name=name)
        p.optimize_attr = {"learning_rate": learning_rate}
        return p

    def create_tensor(self, name=None, dtype=None):
        return Tensor(np.zeros([0], dtype_mod.to_jax_dtype(dtype or self._dtype)))

    def register_buffer(self, name: str, tensor: Tensor, persistable: bool = True):
        if tensor is not None and not isinstance(tensor, Tensor):
            tensor = Tensor(tensor)
        self.__dict__.pop(name, None)
        self._parameters.pop(name, None)
        self._sub_layers.pop(name, None)
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)

    def add_parameter(self, name: str, parameter: Parameter) -> Parameter:
        setattr(self, name, parameter)
        return parameter

    def add_sublayer(self, name: str, sublayer: "Layer") -> "Layer":
        setattr(self, name, sublayer)
        return sublayer

    # --- traversal ---
    def named_parameters(self, prefix="", include_sublayers=True) -> Iterator:
        seen = set()
        for name, layer_prefix, layer in self._walk(prefix):
            for pname, p in layer._parameters.items():
                if p is not None and id(p) not in seen:
                    seen.add(id(p))
                    yield (layer_prefix + pname, p)
            if not include_sublayers:
                break

    def parameters(self, include_sublayers=True) -> list:
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True) -> Iterator:
        seen = set()
        for name, layer_prefix, layer in self._walk(prefix):
            for bname, b in layer._buffers.items():
                if b is not None and id(b) not in seen:
                    seen.add(id(b))
                    yield (layer_prefix + bname, b)
            if not include_sublayers:
                break

    def buffers(self, include_sublayers=True) -> list:
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_sublayers(self, prefix="", include_self=False) -> Iterator:
        if include_self:
            yield (prefix, self)
        for name, sub in self._sub_layers.items():
            if sub is None:
                continue
            sub_prefix = prefix + ("." if prefix else "") + name
            yield (sub_prefix, sub)
            yield from sub.named_sublayers(prefix=sub_prefix)

    def sublayers(self, include_self=False) -> list:
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def children(self) -> Iterator["Layer"]:
        for _, sub in self.named_children():
            yield sub

    def named_children(self) -> Iterator:
        for name, sub in self._sub_layers.items():
            if sub is not None:
                yield name, sub

    def _walk(self, prefix=""):
        """Yield (name, param_prefix, layer) for self and every sublayer."""
        yield ("", prefix, self)
        for name, sub in self._sub_layers.items():
            if sub is None:
                continue
            yield from (
                (n, p, l)
                for n, p, l in sub._walk(prefix + name + ".")
            )

    def apply(self, fn: Callable) -> "Layer":
        for layer in self.sublayers(include_self=True):
            fn(layer)
        return self

    # --- mode ---
    def train(self):
        for layer in self.sublayers(include_self=True):
            layer.training = True
        return self

    def eval(self):
        for layer in self.sublayers(include_self=True):
            layer.training = False
        return self

    # --- hooks ---
    def register_forward_pre_hook(self, hook) -> HookRemoveHelper:
        self._hook_counter += 1
        self._forward_pre_hooks[self._hook_counter] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_counter)

    def register_forward_post_hook(self, hook) -> HookRemoveHelper:
        self._hook_counter += 1
        self._forward_post_hooks[self._hook_counter] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_counter)

    # --- call ---
    def __call__(self, *inputs, **kwargs):
        for hook in list(self._forward_pre_hooks.values()):
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            result = hook(self, inputs, outputs)
            if result is not None:
                outputs = result
        return outputs

    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    # --- state dict ---
    def state_dict(
        self,
        destination=None,
        include_sublayers=True,
        structured_name_prefix="",
        use_hook=True,
    ) -> OrderedDict:
        out = destination if destination is not None else OrderedDict()
        for name, p in self.named_parameters(prefix=structured_name_prefix):
            out[name] = p
        for name, b in self.named_buffers(prefix=structured_name_prefix):
            short = name.rsplit(".", 1)[-1]
            # find owning layer to check persistability
            out[name] = b
        # filter non-persistable buffers
        for name, layer_prefix, layer in self._walk(structured_name_prefix):
            for bname in layer._non_persistable_buffer_names:
                out.pop(layer_prefix + bname, None)
        return out

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        matched = {}
        for name, value in state_dict.items():
            if name in own:
                matched[name] = value
            else:
                unexpected.append(name)
        for name in own:
            if name not in matched:
                missing.append(name)
        for name, value in matched.items():
            target = own[name]
            arr = value.numpy() if isinstance(value, Tensor) else np.asarray(value)
            if list(arr.shape) != list(target.shape):
                raise ValueError(
                    f"state_dict shape mismatch for {name}: "
                    f"{list(arr.shape)} vs {list(target.shape)}"
                )
            target.set_value(arr.astype(target.dtype.np_dtype))
        return missing, unexpected

    load_dict = set_state_dict

    # --- dtype / device movement ---
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            self._to_dtype(dtype)
        return self

    def _to_dtype(self, dtype, include_norms: bool = True):
        want = dtype_mod.convert_dtype(dtype)
        for _, p in self.named_parameters():
            if p.dtype.is_floating:
                p._data = p._data.astype(want.np_dtype)
        for _, b in self.named_buffers():
            if b is not None and b.dtype.is_floating:
                b._data = b._data.astype(want.np_dtype)
        self._dtype = want.name
        return self

    def astype(self, dtype):
        return self._to_dtype(dtype)

    def float(self):
        return self._to_dtype("float32")

    def bfloat16(self):
        return self._to_dtype("bfloat16")

    def float16(self):
        return self._to_dtype("float16")

    def full_name(self):
        return self._name_scope

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, sub in self._sub_layers.items():
            sub_repr = repr(sub).split("\n")
            sub_repr = [sub_repr[0]] + ["  " + l for l in sub_repr[1:]]
            lines.append(f"  ({name}): " + "\n".join(sub_repr))
        body = ""
        if extra and not lines:
            body = extra
        elif lines:
            body = "\n" + "\n".join(lines) + "\n"
        return f"{self.__class__.__name__}({body})"

    def extra_repr(self):
        return ""
