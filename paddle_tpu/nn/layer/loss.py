"""Loss layers (parity: python/paddle/nn/layer/loss.py)."""
from __future__ import annotations

from .. import functional as F
from .layers import Layer


class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean", soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0, name=None):
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction
        self.soft_label = soft_label
        self.axis = axis
        self.use_softmax = use_softmax
        self.label_smoothing = label_smoothing

    def forward(self, input, label):
        return F.cross_entropy(
            input, label, weight=self.weight, ignore_index=self.ignore_index,
            reduction=self.reduction, soft_label=self.soft_label, axis=self.axis,
            use_softmax=self.use_softmax, label_smoothing=self.label_smoothing,
        )


class MSELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.mse_loss(input, label, self.reduction)


class L1Loss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.l1_loss(input, label, self.reduction)


class NLLLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean", name=None):
        super().__init__()
        self.weight, self.ignore_index, self.reduction = weight, ignore_index, reduction

    def forward(self, input, label):
        return F.nll_loss(input, label, self.weight, self.ignore_index, self.reduction)


class BCELoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight, self.reduction = weight, reduction

    def forward(self, input, label):
        return F.binary_cross_entropy(input, label, self.weight, self.reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, weight=None, reduction="mean", pos_weight=None, name=None):
        super().__init__()
        self.weight, self.reduction, self.pos_weight = weight, reduction, pos_weight

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(
            logit, label, self.weight, self.reduction, self.pos_weight
        )


class SmoothL1Loss(Layer):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self.reduction, self.delta = reduction, delta

    def forward(self, input, label):
        return F.smooth_l1_loss(input, label, self.reduction, self.delta)


class HuberLoss(Layer):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self.reduction, self.delta = reduction, delta

    def forward(self, input, label):
        return F.huber_loss(input, label, self.delta, self.reduction)


class KLDivLoss(Layer):
    def __init__(self, reduction="mean", log_target=False):
        super().__init__()
        self.reduction, self.log_target = reduction, log_target

    def forward(self, input, label):
        return F.kl_div(input, label, self.reduction, self.log_target)


class MarginRankingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin, self.reduction = margin, reduction

    def forward(self, input, other, label):
        return F.margin_ranking_loss(input, other, label, self.margin, self.reduction)


class CTCLoss(Layer):
    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self.blank, self.reduction = blank, reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths, norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths, self.blank, self.reduction, norm_by_times)


class CosineEmbeddingLoss(Layer):
    def __init__(self, margin=0, reduction="mean", name=None):
        super().__init__()
        self.margin, self.reduction = margin, reduction

    def forward(self, input1, input2, label):
        return F.cosine_embedding_loss(input1, input2, label, self.margin, self.reduction)


class TripletMarginLoss(Layer):
    def __init__(self, margin=1.0, p=2.0, epsilon=1e-6, swap=False, reduction="mean", name=None):
        super().__init__()
        self.args = (margin, p, epsilon, swap, reduction)

    def forward(self, input, positive, negative):
        return F.triplet_margin_loss(input, positive, negative, *self.args)


class MultiLabelSoftMarginLoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight, self.reduction = weight, reduction

    def forward(self, input, label):
        return F.multi_label_soft_margin_loss(input, label, self.weight, self.reduction)


class SoftMarginLoss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.soft_margin_loss(input, label, self.reduction)


class HingeEmbeddingLoss(Layer):
    def __init__(self, margin=1.0, reduction="mean", name=None):
        super().__init__()
        self.margin, self.reduction = margin, reduction

    def forward(self, input, label):
        return F.hinge_embedding_loss(input, label, self.margin, self.reduction)


class GaussianNLLLoss(Layer):
    def __init__(self, full=False, epsilon=1e-6, reduction="mean", name=None):
        super().__init__()
        self.full, self.epsilon, self.reduction = full, epsilon, reduction

    def forward(self, input, label, variance):
        return F.gaussian_nll_loss(input, label, variance, self.full,
                                   self.epsilon, self.reduction)


class PoissonNLLLoss(Layer):
    def __init__(self, log_input=True, full=False, epsilon=1e-8,
                 reduction="mean", name=None):
        super().__init__()
        self.args = (log_input, full, epsilon, reduction)

    def forward(self, input, label):
        return F.poisson_nll_loss(input, label, *self.args)


class MultiMarginLoss(Layer):
    def __init__(self, p=1, margin=1.0, weight=None, reduction="mean",
                 name=None):
        super().__init__()
        self.p, self.margin = p, margin
        self.weight, self.reduction = weight, reduction

    def forward(self, input, label):
        return F.multi_margin_loss(input, label, self.p, self.margin,
                                   self.weight, self.reduction)


class TripletMarginWithDistanceLoss(Layer):
    def __init__(self, distance_function=None, margin=1.0, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.args = (distance_function, margin, swap, reduction)

    def forward(self, input, positive, negative):
        return F.triplet_margin_with_distance_loss(
            input, positive, negative, *self.args)


class RNNTLoss(Layer):
    def __init__(self, blank=0, fastemit_lambda=0.001, reduction="mean",
                 name=None):
        super().__init__()
        self.blank, self.fastemit = blank, fastemit_lambda
        self.reduction = reduction

    def forward(self, input, label, input_lengths, label_lengths):
        return F.rnnt_loss(input, label, input_lengths, label_lengths,
                           blank=self.blank,
                           fastemit_lambda=self.fastemit,
                           reduction=self.reduction)


class HSigmoidLoss(Layer):
    """Hierarchical sigmoid layer (reference nn/layer/loss.py HSigmoidLoss):
    owns the [num_classes-1, feature_size] tree weights."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 name=None):
        super().__init__()
        self._num_classes = num_classes
        rows = num_classes - 1
        self.weight = self.create_parameter([rows, feature_size],
                                            attr=weight_attr)
        self.bias = (None if bias_attr is False else
                     self.create_parameter([rows], attr=bias_attr,
                                           is_bias=True))

    def forward(self, input, label, path_table=None, path_code=None):
        return F.hsigmoid_loss(input, label, self._num_classes, self.weight,
                               self.bias, path_table, path_code)
