"""Activation layers (parity: python/paddle/nn/layer/activation.py)."""
from __future__ import annotations

from .. import functional as F
from ..initializer import Constant
from .layers import Layer


def _simple(name, fn_name, **fixed):
    def forward(self, x):
        return getattr(F, fn_name)(x, **fixed, **self._kwargs)

    def __init__(self, *args, name=None, **kwargs):
        Layer.__init__(self)
        # positional args map onto the functional's named params in order
        self._kwargs = kwargs
        if args:
            import inspect

            params = [
                p
                for p in inspect.signature(getattr(F, fn_name)).parameters.values()
            ][1:]
            for p, a in zip(params, args):
                self._kwargs[p.name] = a

    return type(name, (Layer,), {"__init__": __init__, "forward": forward})


CELU = _simple("CELU", "celu")
ELU = _simple("ELU", "elu")
GELU = _simple("GELU", "gelu")
Hardshrink = _simple("Hardshrink", "hardshrink")
Hardsigmoid = _simple("Hardsigmoid", "hardsigmoid")
Hardswish = _simple("Hardswish", "hardswish")
Hardtanh = _simple("Hardtanh", "hardtanh")
LeakyReLU = _simple("LeakyReLU", "leaky_relu")
LogSigmoid = _simple("LogSigmoid", "log_sigmoid")
LogSoftmax = _simple("LogSoftmax", "log_softmax")
Maxout = _simple("Maxout", "maxout")
Mish = _simple("Mish", "mish")
ReLU = _simple("ReLU", "relu")
ReLU6 = _simple("ReLU6", "relu6")
SELU = _simple("SELU", "selu")
Sigmoid = _simple("Sigmoid", "sigmoid")
Silu = _simple("Silu", "silu")
Softmax = _simple("Softmax", "softmax")
Softplus = _simple("Softplus", "softplus")
Softshrink = _simple("Softshrink", "softshrink")
Softsign = _simple("Softsign", "softsign")
Swish = _simple("Swish", "swish")
Tanh = _simple("Tanh", "tanh")
Tanhshrink = _simple("Tanhshrink", "tanhshrink")
ThresholdedReLU = _simple("ThresholdedReLU", "thresholded_relu")
GLU = _simple("GLU", "glu")


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._data_format = data_format
        self.weight = self.create_parameter(
            [num_parameters], attr=weight_attr, default_initializer=Constant(init)
        )

    def forward(self, x):
        return F.prelu(x, self.weight, self._data_format)


class RReLU(Layer):
    def __init__(self, lower=1.0 / 8.0, upper=1.0 / 3.0, name=None):
        super().__init__()
        self.lower, self.upper = lower, upper

    def forward(self, x):
        return F.rrelu(x, self.lower, self.upper, training=self.training)


class Softmax2D(Layer):
    """Softmax over the channel dim of NCHW inputs (reference
    nn/layer/activation.py Softmax2D)."""

    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        if x.ndim not in (3, 4):
            raise ValueError(
                f"Softmax2D expects 3-D/4-D input, got {x.ndim}-D")
        return F.softmax(x, axis=-3)
