"""Pooling functionals via lax.reduce_window.

Parity: python/paddle/nn/functional/pooling.py.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ...autograd.engine import apply_op


def _tuple(v, n):
    if v is None:
        return None
    if isinstance(v, int):
        return (v,) * n
    return tuple(int(i) for i in v)


def _pool_pad(padding, n):
    if isinstance(padding, str):
        return padding.upper()
    p = _tuple(padding, n)
    if p is not None and len(p) == n:
        return [(pi, pi) for pi in p]
    return [tuple(x) for x in padding]


def _reduce_pool(x, kernel, stride, pad, n, channel_last, init, op, name):
    kernel = _tuple(kernel, n)
    stride = _tuple(stride, n) if stride is not None else kernel
    padding = _pool_pad(pad, n)

    def fn(v):
        if channel_last:
            dims = (1,) + kernel + (1,)
            strides = (1,) + stride + (1,)
        else:
            dims = (1, 1) + kernel
            strides = (1, 1) + stride
        if isinstance(padding, str):
            pads = padding
        elif channel_last:
            pads = [(0, 0)] + padding + [(0, 0)]
        else:
            pads = [(0, 0), (0, 0)] + padding
        # init must stay a host literal: a traced jnp constant prevents jax
        # from recognizing the max/add monoid, killing reverse-mode under jit
        return jax.lax.reduce_window(v, np.asarray(init, v.dtype), op, dims, strides, pads)

    return apply_op(name, fn, x)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, name=None):
    out = _reduce_pool(x, kernel_size, stride, padding, 1, False, -np.inf, jax.lax.max, "max_pool1d")
    return (out, None) if return_mask else out


def max_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False, return_mask=False, data_format="NCHW", name=None):
    out = _reduce_pool(x, kernel_size, stride, padding, 2, data_format == "NHWC", -np.inf, jax.lax.max, "max_pool2d")
    return (out, None) if return_mask else out


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False, return_mask=False, data_format="NCDHW", name=None):
    out = _reduce_pool(x, kernel_size, stride, padding, 3, data_format == "NDHWC", -np.inf, jax.lax.max, "max_pool3d")
    return (out, None) if return_mask else out


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True, ceil_mode=False, name=None):
    return _avg_pool(x, kernel_size, stride, padding, 1, False, exclusive, "avg_pool1d")


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    return _avg_pool(x, kernel_size, stride, padding, 2, data_format == "NHWC", exclusive, "avg_pool2d", divisor_override)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, divisor_override=None, data_format="NCDHW", name=None):
    return _avg_pool(x, kernel_size, stride, padding, 3, data_format == "NDHWC", exclusive, "avg_pool3d", divisor_override)


def _avg_pool(x, kernel, stride, pad, n, channel_last, exclusive, name, divisor_override=None):
    kernel = _tuple(kernel, n)
    stride = _tuple(stride, n) if stride is not None else kernel
    padding = _pool_pad(pad, n)

    def fn(v):
        if channel_last:
            dims = (1,) + kernel + (1,)
            strides = (1,) + stride + (1,)
            pads = padding if isinstance(padding, str) else [(0, 0)] + padding + [(0, 0)]
        else:
            dims = (1, 1) + kernel
            strides = (1, 1) + stride
            pads = padding if isinstance(padding, str) else [(0, 0), (0, 0)] + padding
        summed = jax.lax.reduce_window(v, jnp.asarray(0, v.dtype), jax.lax.add, dims, strides, pads)
        if divisor_override:
            return summed / divisor_override
        if exclusive:
            ones = jnp.ones_like(v)
            counts = jax.lax.reduce_window(ones, jnp.asarray(0, v.dtype), jax.lax.add, dims, strides, pads)
            return summed / counts
        return summed / np.prod(kernel)

    return apply_op(name, fn, x)


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive(x, output_size, 1, "avg")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive(x, output_size, 2, "avg", data_format == "NHWC")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive(x, output_size, 3, "avg", data_format == "NDHWC")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    out = _adaptive(x, output_size, 1, "max")
    return (out, None) if return_mask else out


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    out = _adaptive(x, output_size, 2, "max")
    return (out, None) if return_mask else out


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    out = _adaptive(x, output_size, 3, "max")
    return (out, None) if return_mask else out


def _adaptive(x, output_size, n, mode, channel_last=False):
    out_sizes = _tuple(output_size, n)

    def fn(v):
        spatial_start = 1 if channel_last else 2
        out = v
        for d in range(n):
            axis = spatial_start + d
            in_size = out.shape[axis]
            want = out_sizes[d] if out_sizes[d] is not None else in_size
            # adaptive pooling: boundaries floor(i*in/out), ceil((i+1)*in/out)
            starts = [int(np.floor(i * in_size / want)) for i in range(want)]
            ends = [int(np.ceil((i + 1) * in_size / want)) for i in range(want)]
            pieces = []
            for s, e in zip(starts, ends):
                seg = jax.lax.slice_in_dim(out, s, e, axis=axis)
                red = jnp.max(seg, axis=axis, keepdims=True) if mode == "max" else jnp.mean(seg, axis=axis, keepdims=True)
                pieces.append(red)
            out = jnp.concatenate(pieces, axis=axis)
        return out

    return apply_op(f"adaptive_{mode}_pool{n}d", fn, x)
