"""Pooling functionals via lax.reduce_window.

Parity: python/paddle/nn/functional/pooling.py.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ...autograd.engine import apply_op


def _tuple(v, n):
    if v is None:
        return None
    if isinstance(v, int):
        return (v,) * n
    return tuple(int(i) for i in v)


def _pool_pad(padding, n):
    if isinstance(padding, str):
        return padding.upper()
    p = _tuple(padding, n)
    if p is not None and len(p) == n:
        return [(pi, pi) for pi in p]
    return [tuple(x) for x in padding]


def _ceil_out_extra(L, k, s, p0, p1, ceil_mode):
    """(output length, extra right padding) for one spatial dim.

    ceil_mode uses ceil instead of floor division (reference
    pooling.py _update_padding semantics / phi pooling infermeta), with the
    constraint that the last window must start inside input + left padding.
    """
    span = L + p0 + p1 - k
    if not ceil_mode:
        return span // s + 1, 0
    out = -(-span // s) + 1
    if (out - 1) * s >= L + p0:
        out -= 1
    return out, max(0, (out - 1) * s + k - (L + p0 + p1))


def _ceil_extras(S, kernel, stride, padding):
    """Per-dim extra right padding a ceil_mode window grid needs beyond the
    user padding — the single source for both the window pads and the
    include-pad divisor (which must NOT count the extra)."""
    return [_ceil_out_extra(S[d], kernel[d], stride[d], p0, p1, True)[1]
            for d, (p0, p1) in enumerate(padding)]


def _ceil_spatial(padding, v, n, kernel, stride, channel_last):
    """Per-dim (left, right+extra) pad pairs implementing ceil_mode."""
    S = v.shape[1:1 + n] if channel_last else v.shape[2:2 + n]
    extras = _ceil_extras(S, kernel, stride, padding)
    return [(p0, p1 + e) for (p0, p1), e in zip(padding, extras)]


def _window_config(v, kernel, stride, padding, n, channel_last, ceil_mode):
    """(dims, strides, pads) for lax.reduce_window — shared by the max and
    avg paths so padding semantics cannot diverge between them."""
    if isinstance(padding, str):
        if ceil_mode and padding == "VALID":
            raise ValueError(
                'When padding is "VALID", ceil_mode must be False '
                "(reference: _update_padding_nd)")
        spatial = padding
    elif ceil_mode:
        spatial = _ceil_spatial(padding, v, n, kernel, stride, channel_last)
    else:
        spatial = padding
    if channel_last:
        dims = (1,) + kernel + (1,)
        strides = (1,) + stride + (1,)
        pads = spatial if isinstance(spatial, str) else [(0, 0)] + spatial + [(0, 0)]
    else:
        dims = (1, 1) + kernel
        strides = (1, 1) + stride
        pads = spatial if isinstance(spatial, str) else [(0, 0), (0, 0)] + spatial
    return dims, strides, pads


def _reduce_pool(x, kernel, stride, pad, n, channel_last, init, op, name,
                 ceil_mode=False):
    kernel = _tuple(kernel, n)
    stride = _tuple(stride, n) if stride is not None else kernel
    padding = _pool_pad(pad, n)

    def fn(v):
        dims, strides, pads = _window_config(
            v, kernel, stride, padding, n, channel_last, ceil_mode)
        # init must stay a host literal: a traced jnp constant prevents jax
        # from recognizing the max/add monoid, killing reverse-mode under jit
        return jax.lax.reduce_window(v, np.asarray(init, v.dtype), op, dims, strides, pads)

    return apply_op(name, fn, x)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, name=None):
    if return_mask:
        return _maxpool_nd_with_mask(x, kernel_size, stride, padding, 1,
                                     False, "max_pool1d", ceil_mode)
    return _reduce_pool(x, kernel_size, stride, padding, 1, False, -np.inf, jax.lax.max, "max_pool1d", ceil_mode)


def max_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False, return_mask=False, data_format="NCHW", name=None):
    if return_mask:
        return _maxpool_nd_with_mask(x, kernel_size, stride, padding, 2,
                                     data_format == "NHWC", "max_pool2d",
                                     ceil_mode)
    return _reduce_pool(x, kernel_size, stride, padding, 2, data_format == "NHWC", -np.inf, jax.lax.max, "max_pool2d", ceil_mode)


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False, return_mask=False, data_format="NCDHW", name=None):
    if return_mask:
        return _maxpool_nd_with_mask(x, kernel_size, stride, padding, 3,
                                     data_format == "NDHWC", "max_pool3d",
                                     ceil_mode)
    return _reduce_pool(x, kernel_size, stride, padding, 3, data_format == "NDHWC", -np.inf, jax.lax.max, "max_pool3d", ceil_mode)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True, ceil_mode=False, name=None):
    return _avg_pool(x, kernel_size, stride, padding, 1, False, exclusive, "avg_pool1d", ceil_mode=ceil_mode)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    return _avg_pool(x, kernel_size, stride, padding, 2, data_format == "NHWC", exclusive, "avg_pool2d", divisor_override, ceil_mode=ceil_mode)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, divisor_override=None, data_format="NCDHW", name=None):
    return _avg_pool(x, kernel_size, stride, padding, 3, data_format == "NDHWC", exclusive, "avg_pool3d", divisor_override, ceil_mode=ceil_mode)


def _avg_pool(x, kernel, stride, pad, n, channel_last, exclusive, name, divisor_override=None, ceil_mode=False):
    kernel = _tuple(kernel, n)
    stride = _tuple(stride, n) if stride is not None else kernel
    padding = _pool_pad(pad, n)

    def fn(v):
        dims, strides, pads = _window_config(
            v, kernel, stride, padding, n, channel_last, ceil_mode)
        # init must stay a HOST literal (np, not jnp): a traced constant
        # hides the add monoid from jax and kills reverse-mode under jit
        # (the eager-cache executable jits this body)
        summed = jax.lax.reduce_window(v, np.asarray(0, v.dtype), jax.lax.add, dims, strides, pads)
        if divisor_override:
            return summed / divisor_override
        if exclusive:
            ones = jnp.ones_like(v)
            counts = jax.lax.reduce_window(ones, np.asarray(0, v.dtype), jax.lax.add, dims, strides, pads)
            return summed / counts
        if ceil_mode and not isinstance(padding, str):
            # include-pad counts cover input + USER padding but not the ceil
            # extra (reference phi pooling clips include-pad windows to the
            # user-padded extent): pad a ones tensor over the user padding
            # and reduce with only the ceil extras as window padding.
            S = v.shape[1:1 + n] if channel_last else v.shape[2:2 + n]
            extras = _ceil_extras(S, kernel, stride, padding)
            z = [(0, 0)]
            ep = [(0, e) for e in extras]
            if channel_last:
                widths, epads = z + list(padding) + z, z + ep + z
            else:
                widths, epads = z + z + list(padding), z + z + ep
            ones = jnp.ones(
                [s + a + b for s, (a, b) in zip(v.shape, widths)], v.dtype)
            counts = jax.lax.reduce_window(
                ones, np.asarray(0, v.dtype), jax.lax.add, dims, strides,
                epads)
            return summed / counts
        return summed / np.prod(kernel)

    return apply_op(name, fn, x)


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive(x, output_size, 1, "avg")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive(x, output_size, 2, "avg", data_format == "NHWC")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive(x, output_size, 3, "avg", data_format == "NDHWC")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    if return_mask:
        return _adaptive_max_with_mask(x, output_size, 1)
    return _adaptive(x, output_size, 1, "max")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    if return_mask:
        return _adaptive_max_with_mask(x, output_size, 2)
    return _adaptive(x, output_size, 2, "max")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    if return_mask:
        return _adaptive_max_with_mask(x, output_size, 3)
    return _adaptive(x, output_size, 3, "max")


def _adaptive_max_with_mask(x, output_size, n):
    out_sizes = _tuple(output_size, n)

    def fn(v):
        S = v.shape[2:]
        starts_list, ends_list, kmax = [], [], []
        for d in range(n):
            want = out_sizes[d] if out_sizes[d] is not None else S[d]
            i = np.arange(want)
            starts = np.floor(i * S[d] / want).astype(np.int32)
            ends = np.ceil((i + 1) * S[d] / want).astype(np.int32)
            starts_list.append(jnp.asarray(starts))
            ends_list.append(jnp.asarray(ends))
            kmax.append(int((ends - starts).max()))
        pooled, mask = _max_pool_with_mask(v, starts_list, tuple(kmax),
                                           ends_list)
        return pooled, mask.astype(jnp.int32)

    return apply_op(f"adaptive_max_pool{n}d", fn, x)


def _adaptive(x, output_size, n, mode, channel_last=False):
    out_sizes = _tuple(output_size, n)

    def fn(v):
        spatial_start = 1 if channel_last else 2
        out = v
        for d in range(n):
            axis = spatial_start + d
            in_size = out.shape[axis]
            want = out_sizes[d] if out_sizes[d] is not None else in_size
            # adaptive pooling: boundaries floor(i*in/out), ceil((i+1)*in/out)
            starts = [int(np.floor(i * in_size / want)) for i in range(want)]
            ends = [int(np.ceil((i + 1) * in_size / want)) for i in range(want)]
            pieces = []
            for s, e in zip(starts, ends):
                seg = jax.lax.slice_in_dim(out, s, e, axis=axis)
                red = jnp.max(seg, axis=axis, keepdims=True) if mode == "max" else jnp.mean(seg, axis=axis, keepdims=True)
                pieces.append(red)
            out = jnp.concatenate(pieces, axis=axis)
        return out

    return apply_op(f"adaptive_{mode}_pool{n}d", fn, x)


# ---------------------------------------------------------------------------
# max pool with argmax mask, unpool, fractional pools
# (reference: phi/kernels/funcs/pooling.h MaxPoolWithIndex/FractionalMaxPool,
#  phi/kernels/gpu/unpool_kernel.cu)
# ---------------------------------------------------------------------------


def _gather_windows(v, starts_list, kernel, ends_list=None):
    """Gather pooling windows via advanced indexing.

    v: [N, C, *S]. starts_list[d]: [out_d] window start coords (may be
    traced, e.g. fractional pooling). Returns (windows [N, C, *out, *kernel],
    valid mask broadcastable to windows). ``ends_list`` optionally bounds
    each window (variable-size regions); defaults to start + kernel."""
    n = len(starts_list)
    S = v.shape[2:]
    coords = []
    valids = []
    for d in range(n):
        starts = starts_list[d]
        offs = jnp.arange(kernel[d])
        c = starts[:, None] + offs[None, :]  # [out_d, k_d]
        hi = (ends_list[d][:, None] if ends_list is not None
              else starts[:, None] + kernel[d])
        valid = (c >= 0) & (c < S[d]) & (c < hi)
        # reshape for broadcasting: dim d occupies axes (2+d) and (2+n+d)
        shape = [1] * (2 * n)
        shape[d] = c.shape[0]
        shape[n + d] = c.shape[1]
        coords.append(jnp.clip(c, 0, S[d] - 1).reshape(shape))
        valids.append(valid.reshape(shape))
    windows = v[(slice(None), slice(None), *coords)]
    valid = valids[0]
    for m in valids[1:]:
        valid = valid & m
    return windows, valid, coords


def _max_pool_with_mask(v, starts_list, kernel, ends_list=None):
    """(pooled, flat-input-spatial argmax indices) for [N, C, *S] input."""
    n = len(starts_list)
    S = v.shape[2:]
    windows, valid, coords = _gather_windows(v, starts_list, kernel,
                                             ends_list)
    neg = jnp.asarray(-np.inf if jnp.issubdtype(v.dtype, jnp.floating)
                      else jnp.iinfo(v.dtype).min, v.dtype)
    windows = jnp.where(valid, windows, neg)
    N, C = v.shape[:2]
    out_sizes = tuple(s.shape[0] for s in starts_list)
    K = int(np.prod(kernel))
    flat = windows.reshape(N, C, *out_sizes, K)
    kidx = jnp.argmax(flat, axis=-1)
    pooled = jnp.max(flat, axis=-1)
    # decompose kidx -> per-dim offsets -> input coords -> flat spatial idx
    flat_idx = jnp.zeros_like(kidx)
    rem = kidx
    for d in range(n):
        kstride = int(np.prod(kernel[d + 1:]))
        off = rem // kstride
        rem = rem % kstride
        # coords[d] has out_d at axis d of a 2n-dim layout; rebuild per-out
        starts = starts_list[d]
        shape = [1, 1] + [1] * n
        shape[2 + d] = starts.shape[0]
        coord_d = starts.reshape(shape) + off
        sstride = int(np.prod(S[d + 1:]))
        flat_idx = flat_idx + coord_d * sstride
    return pooled, flat_idx


def _maxpool_nd_with_mask(x, kernel_size, stride, padding, n, channel_last,
                          name, ceil_mode=False):
    kernel = _tuple(kernel_size, n)
    stride_t = _tuple(stride, n) if stride is not None else kernel
    padding_pairs = _pool_pad(padding, n)
    if isinstance(padding_pairs, str):
        raise ValueError(
            f"{name}: string padding unsupported with return_mask=True")

    def fn(v):
        if channel_last:
            perm = (0, n + 1) + tuple(range(1, n + 1))
            v = jnp.transpose(v, perm)
        S = v.shape[2:]
        starts_list = []
        for d in range(n):
            p0 = padding_pairs[d][0]
            out_d, _ = _ceil_out_extra(S[d], kernel[d], stride_t[d],
                                       p0, padding_pairs[d][1], ceil_mode)
            starts_list.append(jnp.arange(out_d) * stride_t[d] - p0)
        pooled, mask = _max_pool_with_mask(v, starts_list, kernel)
        if channel_last:
            perm_back = (0,) + tuple(range(2, n + 2)) + (1,)
            pooled = jnp.transpose(pooled, perm_back)
            mask = jnp.transpose(mask, perm_back)
        return pooled, mask.astype(jnp.int32)

    return apply_op(name, fn, x)


def _unpool_nd(x, indices, kernel_size, stride, padding, output_size, n,
               channel_last, name):
    kernel = _tuple(kernel_size, n)
    stride_t = _tuple(stride, n) if stride is not None else kernel
    pad_t = _tuple(padding, n)

    def fn(v, idx):
        if channel_last:
            perm = (0, n + 1) + tuple(range(1, n + 1))
            v = jnp.transpose(v, perm)
            idx = jnp.transpose(idx, perm)
        N, C = v.shape[:2]
        S = v.shape[2:]
        if output_size is not None:
            out_sizes = tuple(int(s) for s in output_size)[-n:]
        else:
            out_sizes = tuple(
                (S[d] - 1) * stride_t[d] - 2 * pad_t[d] + kernel[d]
                for d in range(n))
        flat_out = int(np.prod(out_sizes))
        out = jnp.zeros((N, C, flat_out), v.dtype)
        vi = v.reshape(N, C, -1)
        ii = idx.reshape(N, C, -1).astype(jnp.int32)
        bidx = jnp.arange(N)[:, None, None]
        cidx = jnp.arange(C)[None, :, None]
        out = out.at[bidx, cidx, ii].set(vi)
        out = out.reshape(N, C, *out_sizes)
        if channel_last:
            perm_back = (0,) + tuple(range(2, n + 2)) + (1,)
            out = jnp.transpose(out, perm_back)
        return out

    return apply_op(name, fn, x, indices)


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    return _unpool_nd(x, indices, kernel_size, stride, padding, output_size,
                      1, data_format == "NLC", "max_unpool1d")


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    return _unpool_nd(x, indices, kernel_size, stride, padding, output_size,
                      2, data_format == "NHWC", "max_unpool2d")


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    return _unpool_nd(x, indices, kernel_size, stride, padding, output_size,
                      3, data_format == "NDHWC", "max_unpool3d")


def _fractional_pool(x, output_size, kernel_size, random_u, return_mask, n,
                     name):
    """Fractional max pooling (Graham 2014; reference FractionalMaxPool in
    phi/kernels/funcs/pooling.h): region edges ceil(alpha*(i+u)) with a
    (pseudo)random u in (0,1); fixed ``kernel_size`` overrides region ends."""
    from ...framework.random import rng_arg

    out_sizes = _tuple(output_size, n)

    def fn(v, u):
        S = v.shape[2:]
        if u is None:
            raise AssertionError  # handled by wrapper
        starts_list, ends_list = [], []
        for d in range(n):
            out_d = out_sizes[d]
            alpha = S[d] / out_d
            i = jnp.arange(out_d + 1, dtype=jnp.float32)
            edges = jnp.ceil(alpha * (i + u)) - jnp.ceil(alpha * u)
            edges = jnp.clip(edges.astype(jnp.int32), 0, S[d])
            starts_list.append(edges[:-1])
            if kernel_size is not None:
                k = _tuple(kernel_size, n)[d]
                ends_list.append(jnp.minimum(edges[:-1] + k, S[d]))
            else:
                ends_list.append(edges[1:])
        kmax = tuple(
            (_tuple(kernel_size, n)[d] if kernel_size is not None
             else int(np.ceil(S[d] / out_sizes[d])) + 1)
            for d in range(n))
        pooled, mask = _max_pool_with_mask(v, starts_list, kmax, ends_list)
        return pooled, mask.astype(jnp.int32)

    if random_u is None:
        karg = rng_arg()

        def fn_rand(v, key):
            u = jax.random.uniform(key, (), jnp.float32, 1e-3, 1.0 - 1e-3)
            return fn(v, u)

        out, mask = apply_op(name, fn_rand, x, karg)
    else:
        out, mask = apply_op(name, lambda v: fn(v, jnp.float32(random_u)), x)
    return (out, mask) if return_mask else out


def fractional_max_pool2d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    return _fractional_pool(x, output_size, kernel_size, random_u,
                            return_mask, 2, "fractional_max_pool2d")


def fractional_max_pool3d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    return _fractional_pool(x, output_size, kernel_size, random_u,
                            return_mask, 3, "fractional_max_pool3d")


def _lp_pool(x, norm_type, kernel, stride, pad, n, channel_last, ceil_mode,
             name):
    """LP pooling: (sum |x|^p over window)^(1/p); p=inf -> max pool
    (reference: nn/functional/pooling.py lp_pool1d/2d)."""
    p = float(norm_type)
    kernel = _tuple(kernel, n)
    stride = _tuple(stride, n) if stride is not None else kernel
    padding = _pool_pad(pad, n)
    if np.isinf(p):
        return _reduce_pool(x, kernel, stride, pad, n, channel_last,
                            -np.inf, jax.lax.max, name, ceil_mode)

    def fn(v):
        dims, strides, pads = _window_config(
            v, kernel, stride, padding, n, channel_last, ceil_mode)
        powed = jnp.abs(v) ** p
        s = jax.lax.reduce_window(
            powed, np.asarray(0, v.dtype), jax.lax.add, dims, strides, pads)
        return s ** (1.0 / p)

    return apply_op(name, fn, x)


def lp_pool1d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCL", name=None):
    return _lp_pool(x, norm_type, kernel_size, stride, padding, 1,
                    data_format == "NLC", ceil_mode, "lp_pool1d")


def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCHW", name=None):
    return _lp_pool(x, norm_type, kernel_size, stride, padding, 2,
                    data_format == "NHWC", ceil_mode, "lp_pool2d")
