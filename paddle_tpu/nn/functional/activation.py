"""Activation functionals (parity: python/paddle/nn/functional/activation.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...autograd.engine import apply_op, make_op

relu = make_op("relu", jax.nn.relu)
relu6 = make_op("relu6", jax.nn.relu6)
sigmoid = make_op("sigmoid", jax.nn.sigmoid)
log_sigmoid = make_op("log_sigmoid", jax.nn.log_sigmoid)
tanh = make_op("tanh", jnp.tanh)
silu = make_op("silu", jax.nn.silu)
swish = silu
mish = make_op("mish", lambda x: x * jnp.tanh(jax.nn.softplus(x)))
tanhshrink = make_op("tanhshrink", lambda x: x - jnp.tanh(x))
softsign = make_op("softsign", jax.nn.soft_sign)


def gelu(x, approximate=False, name=None):
    return apply_op("gelu", lambda v: jax.nn.gelu(v, approximate=approximate), x)


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply_op("leaky_relu", lambda v: jax.nn.leaky_relu(v, negative_slope), x)


def elu(x, alpha=1.0, name=None):
    return apply_op("elu", lambda v: jax.nn.elu(v, alpha), x)


def elu_(x, alpha=1.0, name=None):
    from ...tensor.manipulation import _inplace

    return _inplace(x, elu(x, alpha))


def selu(
    x,
    scale=1.0507009873554804934193349852946,
    alpha=1.6732632423543772848170429916717,
    name=None,
):
    return apply_op(
        "selu", lambda v: scale * jnp.where(v > 0, v, alpha * jnp.expm1(v)), x
    )


def celu(x, alpha=1.0, name=None):
    return apply_op("celu", lambda v: jax.nn.celu(v, alpha), x)


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return apply_op("hardtanh", lambda v: jnp.clip(v, min, max), x)


def hardshrink(x, threshold=0.5, name=None):
    return apply_op(
        "hardshrink", lambda v: jnp.where(jnp.abs(v) > threshold, v, 0.0), x
    )


def softshrink(x, threshold=0.5, name=None):
    return apply_op(
        "softshrink",
        lambda v: jnp.where(
            v > threshold, v - threshold, jnp.where(v < -threshold, v + threshold, 0.0)
        ),
        x,
    )


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return apply_op("hardsigmoid", lambda v: jnp.clip(v * slope + offset, 0.0, 1.0), x)


def hardswish(x, name=None):
    return apply_op("hardswish", lambda v: v * jnp.clip(v + 3.0, 0.0, 6.0) / 6.0, x)


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return apply_op(
        "softplus",
        lambda v: jnp.where(v * beta > threshold, v, jax.nn.softplus(v * beta) / beta),
        x,
    )


def prelu(x, weight, data_format="NCHW", name=None):
    def fn(v, w):
        if w.size == 1:
            return jnp.where(v >= 0, v, w.reshape(()) * v)
        ax = 1 if data_format[1] == "C" else v.ndim - 1
        shape = [1] * v.ndim
        shape[ax] = w.size
        return jnp.where(v >= 0, v, w.reshape(shape) * v)

    return apply_op("prelu", fn, x, weight)


def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, training=True, name=None):
    from ...framework.random import rng_arg

    if training:
        def fn(v, key):
            alpha = jax.random.uniform(key, v.shape, v.dtype, lower, upper)
            return jnp.where(v >= 0, v, alpha * v)

        return apply_op("rrelu", fn, x, rng_arg())
    mid = (lower + upper) / 2.0
    return apply_op("rrelu", lambda v: jnp.where(v >= 0, v, mid * v), x)


def softmax(x, axis=-1, dtype=None, name=None):
    def fn(v):
        if dtype is not None:
            from ...framework.dtype import to_jax_dtype

            v = v.astype(to_jax_dtype(dtype))
        return jax.nn.softmax(v, axis=axis)

    return apply_op("softmax", fn, x)


def softmax_(x, axis=-1, dtype=None, name=None):
    from ...tensor.manipulation import _inplace

    return _inplace(x, softmax(x, axis, dtype))


def log_softmax(x, axis=-1, dtype=None, name=None):
    def fn(v):
        if dtype is not None:
            from ...framework.dtype import to_jax_dtype

            v = v.astype(to_jax_dtype(dtype))
        return jax.nn.log_softmax(v, axis=axis)

    return apply_op("log_softmax", fn, x)


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ...framework.random import rng_arg

    def fn(v, key):
        g = -jnp.log(-jnp.log(jax.random.uniform(key, v.shape) + 1e-20) + 1e-20)
        y = jax.nn.softmax((v + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            one_hot = jnp.zeros_like(y)
            one_hot = jnp.put_along_axis(one_hot, idx, 1.0, axis=axis, inplace=False)
            y = one_hot + y - jax.lax.stop_gradient(y)
        return y

    return apply_op("gumbel_softmax", fn, x, rng_arg())


def maxout(x, groups, axis=1, name=None):
    def fn(v):
        shape = list(v.shape)
        c = shape[axis]
        shape[axis : axis + 1] = [c // groups, groups]
        return jnp.max(v.reshape(shape), axis=axis + 1)

    return apply_op("maxout", fn, x)


def glu(x, axis=-1, name=None):
    return apply_op("glu", lambda v: jax.nn.glu(v, axis=axis), x)


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    return apply_op(
        "thresholded_relu", lambda v: jnp.where(v > threshold, v, value), x
    )


def relu_(x, name=None):
    from ...tensor.manipulation import _inplace

    return _inplace(x, relu(x))


def tanh_(x, name=None):
    from ...tensor.manipulation import _inplace

    return _inplace(x, tanh(x))


def hardtanh_(x, min=-1.0, max=1.0, name=None):
    """In-place hardtanh (reference exports the op_ spelling)."""
    from ...tensor.manipulation import _inplace

    return _inplace(x, hardtanh(x, min, max))


def leaky_relu_(x, negative_slope=0.01, name=None):
    from ...tensor.manipulation import _inplace

    return _inplace(x, leaky_relu(x, negative_slope))


def thresholded_relu_(x, threshold=1.0, value=0.0, name=None):
    from ...tensor.manipulation import _inplace

    return _inplace(x, thresholded_relu(x, threshold, value))
