"""Common functionals: linear, dropout, embedding, padding, one_hot,
interpolate, unfold, cosine_similarity.

Parity: python/paddle/nn/functional/common.py + input.py.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ...autograd.engine import apply_op
from ...framework.random import default_generator, rng_arg
from ...tensor.tensor import Tensor


def linear(x, weight, bias=None, name=None):
    """y = x @ W + b with W[in, out] (paddle convention)."""
    if bias is not None:
        return apply_op("linear", lambda v, w, b: jnp.matmul(v, w) + b, x, weight, bias)
    return apply_op("linear", lambda v, w: jnp.matmul(v, w), x, weight)


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    if not training or p == 0.0:
        return x.clone() if isinstance(x, Tensor) else x

    def fn(v, key):
        shape = list(v.shape)
        if axis is not None:
            axes = [axis] if isinstance(axis, int) else list(axis)
            mask_shape = [s if i in [a % v.ndim for a in axes] else 1 for i, s in enumerate(shape)]
        else:
            mask_shape = shape
        keep = jax.random.bernoulli(key, 1.0 - p, mask_shape)
        if mode == "upscale_in_train":
            return jnp.where(keep, v / (1.0 - p), jnp.zeros((), v.dtype)).astype(v.dtype)
        return jnp.where(keep, v, jnp.zeros((), v.dtype))

    return apply_op("dropout", fn, x, rng_arg())


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p=p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p=p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x.clone()
    alpha = 1.6732632423543772848170429916717
    scale = 1.0507009873554804934193349852946
    alpha_p = -alpha * scale

    def fn(v, key):
        keep = jax.random.bernoulli(key, 1.0 - p, v.shape)
        a = (1.0 / np.sqrt((1.0 - p) * (1.0 + p * alpha_p**2))).astype(np.float32)
        b = -a * alpha_p * p
        return (a * jnp.where(keep, v, jnp.asarray(alpha_p, v.dtype)) + b).astype(v.dtype)

    return apply_op("alpha_dropout", fn, x, rng_arg())


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    def fn(ids, w):
        out = jnp.take(w, ids, axis=0)
        if padding_idx is not None:
            mask = (ids != padding_idx)[..., None]
            out = out * mask.astype(out.dtype)
        return out

    return apply_op("embedding", fn, x, weight)


def one_hot(x, num_classes, name=None):
    return apply_op(
        "one_hot", lambda v: jax.nn.one_hot(v, num_classes, dtype=jnp.float32), x
    )


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", pad_from_left_axis=False, name=None):
    from ...tensor.manipulation import _int_list

    pad = _int_list(pad)

    def fn(v):
        nd = v.ndim
        if len(pad) == 2 * nd:
            # full-form: paddle orders [before0, after0, before1, after1, ...]
            widths = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
        else:
            # partial form applies to the spatial dims per data_format,
            # ordered from the LAST spatial dim backwards (torch-style).
            widths = [(0, 0)] * nd
            n_spatial = len(pad) // 2
            if data_format.startswith("N") and data_format[1] == "C":
                spatial = list(range(2, nd))
            else:
                spatial = list(range(1, nd - 1))
            for i in range(n_spatial):
                dim = spatial[len(spatial) - 1 - i]
                widths[dim] = (pad[2 * i], pad[2 * i + 1])
        jmode = {"constant": "constant", "reflect": "reflect", "replicate": "edge", "circular": "wrap"}[mode]
        if jmode == "constant":
            return jnp.pad(v, widths, mode=jmode, constant_values=value)
        return jnp.pad(v, widths, mode=jmode)

    return apply_op("pad", fn, x)


def zeropad2d(x, padding, data_format="NCHW", name=None):
    return pad(x, padding, mode="constant", value=0.0, data_format=data_format)


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    def fn(a, b):
        dot = jnp.sum(a * b, axis=axis)
        na = jnp.sqrt(jnp.sum(a * a, axis=axis))
        nb = jnp.sqrt(jnp.sum(b * b, axis=axis))
        return dot / jnp.maximum(na * nb, eps)

    return apply_op("cosine_similarity", fn, x1, x2)


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def fn(v):
        norm = jnp.sum(jnp.abs(v) ** p, axis=axis, keepdims=True) ** (1.0 / p)
        return v / jnp.maximum(norm, epsilon)

    return apply_op("normalize", fn, x)


def interpolate(
    x,
    size=None,
    scale_factor=None,
    mode="nearest",
    align_corners=False,
    align_mode=0,
    data_format="NCHW",
    name=None,
):
    def fn(v):
        channel_last = data_format in ("NHWC", "NWC", "NDHWC")
        spatial_ndim = v.ndim - 2
        if channel_last:
            spatial = v.shape[1:-1]
        else:
            spatial = v.shape[2:]
        if size is not None:
            out_spatial = [int(s.item() if isinstance(s, Tensor) else s) for s in (size if isinstance(size, (list, tuple)) else [size])]
        else:
            sf = scale_factor if isinstance(scale_factor, (list, tuple)) else [scale_factor] * spatial_ndim
            out_spatial = [int(s * f) for s, f in zip(spatial, sf)]
        method = {"nearest": "nearest", "bilinear": "linear", "trilinear": "linear", "linear": "linear", "bicubic": "cubic", "area": "linear"}[mode]
        if channel_last:
            out_shape = (v.shape[0], *out_spatial, v.shape[-1])
        else:
            out_shape = (v.shape[0], v.shape[1], *out_spatial)
        return jax.image.resize(v, out_shape, method=method).astype(v.dtype)

    return apply_op("interpolate", fn, x)


def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False, align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode, data_format)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor

    def fn(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            v = v.reshape(n, c // (r * r), r, r, h, w)
            v = v.transpose(0, 1, 4, 2, 5, 3)
            return v.reshape(n, c // (r * r), h * r, w * r)
        n, h, w, c = v.shape
        v = v.reshape(n, h, w, r, r, c // (r * r))
        v = v.transpose(0, 1, 3, 2, 4, 5)
        return v.reshape(n, h * r, w * r, c // (r * r))

    return apply_op("pixel_shuffle", fn, x)


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = downscale_factor

    def fn(v):
        n, c, h, w = v.shape
        v = v.reshape(n, c, h // r, r, w // r, r)
        v = v.transpose(0, 1, 3, 5, 2, 4)
        return v.reshape(n, c * r * r, h // r, w // r)

    return apply_op("pixel_unshuffle", fn, x)


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    def fn(v):
        n, c, h, w = v.shape
        v = v.reshape(n, groups, c // groups, h, w)
        return v.transpose(0, 2, 1, 3, 4).reshape(n, c, h, w)

    return apply_op("channel_shuffle", fn, x)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col: [N,C,H,W] -> [N, C*kh*kw, L] (paddle semantics)."""

    def _pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)

    kh, kw = _pair(kernel_sizes)
    sh, sw = _pair(strides)
    ph, pw = _pair(paddings) if not (isinstance(paddings, (list, tuple)) and len(paddings) == 4) else (paddings[0], paddings[1])
    dh, dw = _pair(dilations)

    def fn(v):
        n, c, h, w = v.shape
        v = jnp.pad(v, [(0, 0), (0, 0), (ph, ph), (pw, pw)])
        out_h = (h + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
        out_w = (w + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
        patches = jax.lax.conv_general_dilated_patches(
            v, (kh, kw), (sh, sw), "VALID", rhs_dilation=(dh, dw),
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
        )  # [N, C*kh*kw, out_h, out_w]
        return patches.reshape(n, c * kh * kw, out_h * out_w)

    return apply_op("unfold", fn, x)


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    def _pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)

    oh, ow = _pair(output_sizes)
    kh, kw = _pair(kernel_sizes)
    sh, sw = _pair(strides)
    ph, pw = _pair(paddings)
    dh, dw = _pair(dilations)

    def fn(v):
        n, ckk, L = v.shape
        c = ckk // (kh * kw)
        out_h = (oh + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
        out_w = (ow + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
        v = v.reshape(n, c, kh, kw, out_h, out_w)
        out = jnp.zeros((n, c, oh + 2 * ph, ow + 2 * pw), v.dtype)
        for i in range(kh):
            for j in range(kw):
                hi = i * dh
                wi = j * dw
                out = out.at[
                    :, :, hi : hi + out_h * sh : sh, wi : wi + out_w * sw : sw
                ].add(v[:, :, i, j])
        return out[:, :, ph : ph + oh, pw : pw + ow]

    return apply_op("fold", fn, x)


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def fn(l, *rest):
        k = l.shape[-1]
        if rest:
            return (1 - epsilon) * l + epsilon * rest[0]
        return (1 - epsilon) * l + epsilon / k

    args = (label, prior_dist) if prior_dist is not None else (label,)
    return apply_op("label_smooth", fn, *args)


def bilinear(x1, x2, weight, bias=None, name=None):
    def fn(a, b, w, *rest):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if rest:
            out = out + rest[0]
        return out

    args = (x1, x2, weight) + ((bias,) if bias is not None else ())
    return apply_op("bilinear", fn, *args)


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """Sample x [N,C,H,W] at normalized grid [N,Hg,Wg,2] locations
    (reference: nn/functional/vision.py grid_sample — the STN sampler).
    Grid coords in [-1, 1]; modes bilinear/nearest; padding zeros/border/
    reflection."""
    if mode not in ("bilinear", "nearest"):
        raise ValueError(f"grid_sample mode must be bilinear|nearest, got {mode!r}")
    if padding_mode not in ("zeros", "border", "reflection"):
        raise ValueError(
            f"padding_mode must be zeros|border|reflection, got {padding_mode!r}")

    def fn(x_, g):
        N, C, H, W = x_.shape
        gx, gy = g[..., 0], g[..., 1]
        if align_corners:
            fx = (gx + 1) * 0.5 * (W - 1)
            fy = (gy + 1) * 0.5 * (H - 1)
        else:
            fx = ((gx + 1) * W - 1) * 0.5
            fy = ((gy + 1) * H - 1) * 0.5

        if padding_mode == "reflection":
            def reflect(f, size):
                if size == 1:
                    return jnp.zeros_like(f)
                if align_corners:
                    # fold about pixel CENTERS: [0, size-1], period 2(size-1)
                    period = 2.0 * (size - 1)
                    f = jnp.abs(jnp.mod(f, period))
                    return jnp.where(f > size - 1, period - f, f)
                # fold about pixel EDGES: [-0.5, size-0.5], period 2*size
                period = 2.0 * size
                g = jnp.abs(jnp.mod(f + 0.5, period))
                g = jnp.where(g > size, period - g, g)
                return jnp.clip(g - 0.5, 0, size - 1)

            fx = reflect(fx, W)
            fy = reflect(fy, H)

        def sample_nearest(feat, fy_, fx_):
            ix = jnp.round(fx_).astype(jnp.int32)
            iy = jnp.round(fy_).astype(jnp.int32)
            valid = (ix >= 0) & (ix < W) & (iy >= 0) & (iy < H)
            ixc = jnp.clip(ix, 0, W - 1)
            iyc = jnp.clip(iy, 0, H - 1)
            out = feat[:, iyc, ixc]
            if padding_mode == "zeros":
                out = jnp.where(valid[None], out, 0.0)
            return out

        def sample_bilinear(feat, fy_, fx_):
            x0 = jnp.floor(fx_)
            y0 = jnp.floor(fy_)
            wx = fx_ - x0
            wy = fy_ - y0
            out = 0.0
            for dy, dx, w in ((0, 0, (1 - wy) * (1 - wx)),
                              (0, 1, (1 - wy) * wx),
                              (1, 0, wy * (1 - wx)),
                              (1, 1, wy * wx)):
                ix = (x0 + dx).astype(jnp.int32)
                iy = (y0 + dy).astype(jnp.int32)
                valid = (ix >= 0) & (ix < W) & (iy >= 0) & (iy < H)
                v = feat[:, jnp.clip(iy, 0, H - 1), jnp.clip(ix, 0, W - 1)]
                if padding_mode == "zeros":
                    v = jnp.where(valid[None], v, 0.0)
                out = out + v * w[None]
            return out

        sampler = sample_nearest if mode == "nearest" else sample_bilinear
        return jax.vmap(sampler)(x_, fy, fx)

    return apply_op("grid_sample", fn, x, grid)


def pdist(x, p=2.0, name=None):
    """p-norm distance between every pair of row vectors (reference
    nn/functional/distance.py:111). Output shape N*(N-1)/2.

    TPU formulation: the upper-triangle index set is static given N, so it is
    built host-side and the device does a dense pairwise-distance einsum plus
    one static gather — no boolean masked_select (dynamic shapes defeat XLA).
    """
    if len(x.shape) != 2:
        raise ValueError(f"pdist expects a 2-D tensor, got shape {x.shape}")
    if p < 0:
        raise ValueError(f"pdist: p must be non-negative, got {p}")
    n = int(x.shape[0])
    iu = np.triu_indices(n, k=1)
    rows, cols = jnp.asarray(iu[0]), jnp.asarray(iu[1])

    def fn(v):
        diff = v[rows] - v[cols]  # (n*(n-1)/2, M): only needed pairs
        absd = jnp.abs(diff)
        if p == 0:
            return jnp.sum((absd != 0).astype(v.dtype), axis=-1)
        if p == float("inf"):
            return jnp.max(absd, axis=-1)
        if p == 2.0:
            return jnp.sqrt(jnp.sum(diff * diff, axis=-1))
        return jnp.sum(absd ** p, axis=-1) ** (1.0 / p)

    return apply_op("pdist", fn, x)


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    """p-norm distance between paired rows of x and y (reference
    nn/functional/distance.py pairwise_distance; the PairwiseDistance layer
    wraps this)."""
    def fn(a, b):
        d = a - b + epsilon
        if p == float("inf"):
            out = jnp.max(jnp.abs(d), axis=-1, keepdims=keepdim)
        elif p == 0:
            out = jnp.sum((d != 0).astype(a.dtype), axis=-1, keepdims=keepdim)
        else:
            out = jnp.sum(jnp.abs(d) ** p, axis=-1, keepdims=keepdim) ** (1.0 / p)
        return out

    return apply_op("pairwise_distance", fn, x, y)
