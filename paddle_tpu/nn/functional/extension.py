"""Extension functionals: spatial transforms, sequence/beam utilities,
margin softmax, RNN-T loss.

Parity targets (reference file:line cited per op):
- affine_grid      phi/kernels/impl/affine_grid_kernel_impl.h
- temporal_shift   phi/kernels/gpu/temporal_shift_kernel.cu (TSM)
- gather_tree      phi/kernels/gpu/gather_tree_kernel.cu
- edit_distance    phi/kernels/gpu/edit_distance_kernel.cu
- rnnt_loss        phi/kernels warprnnt (external lib in the reference;
                   implemented natively here as a log-space DP under scan)
- class_center_sample / margin_cross_entropy
                   phi/kernels/gpu/class_center_sample_kernel.cu,
                   margin_cross_entropy_kernel.cu (PLSC / ArcFace family)

TPU-native notes: everything is static-shape; dynamic-length semantics ride
masks and scalar lengths, DP recurrences are ``lax.scan`` (compiler-friendly
control flow), sampling threads PRNG keys as op args (static replay safe).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ...autograd.engine import apply_op
from ...framework.random import rng_arg
from ...tensor.tensor import Tensor

__all__ = [
    "affine_grid", "temporal_shift", "gather_tree", "edit_distance",
    "rnnt_loss", "class_center_sample", "margin_cross_entropy",
    "sequence_mask",
]


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """y[..., j] = j < x[...] — the classic length→mask op.

    Reference: python/paddle/nn/functional/extension.py:43 (sequence_mask,
    SequenceMaskScalarInferMeta in phi/infermeta/unary.cc). When ``maxlen``
    is None the reference sizes the mask from max(x) — a data-dependent
    output shape, so it is resolved EAGERLY here (one host sync) and the
    op body stays static-shape for XLA.
    """
    from ...framework.dtype import to_jax_dtype  # local: avoid cycles

    xv = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    if maxlen is None:
        maxlen = int(jnp.max(xv))
    ml = int(maxlen)

    def fn(v):
        mask = jnp.arange(ml) < v[..., None]
        return mask.astype(to_jax_dtype(dtype))

    return apply_op("sequence_mask", fn, x)


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """Generate a 2D/3D sampling grid for ``grid_sample``.

    theta [N,2,3] + out_shape [N,C,H,W] -> grid [N,H,W,2];
    theta [N,3,4] + out_shape [N,C,D,H,W] -> grid [N,D,H,W,3].
    """
    if isinstance(out_shape, Tensor):
        out_shape = [int(v) for v in np.asarray(out_shape._data)]
    out_shape = [int(v) for v in out_shape]

    def lin(n):
        if align_corners:
            return jnp.linspace(-1.0, 1.0, n)
        # half-pixel centers
        step = 2.0 / n
        return jnp.linspace(-1.0 + step / 2.0, 1.0 - step / 2.0, n)

    def fn(th):
        if th.shape[-2:] == (2, 3):
            N, H, W = out_shape[0], out_shape[2], out_shape[3]
            ys, xs = jnp.meshgrid(lin(H), lin(W), indexing="ij")
            base = jnp.stack([xs, ys, jnp.ones_like(xs)], axis=-1)  # [H,W,3]
            grid = jnp.einsum("hwk,njk->nhwj", base.astype(th.dtype), th)
            return grid  # [N,H,W,2]
        N, D, H, W = out_shape[0], out_shape[2], out_shape[3], out_shape[4]
        zs, ys, xs = jnp.meshgrid(lin(D), lin(H), lin(W), indexing="ij")
        base = jnp.stack([xs, ys, zs, jnp.ones_like(xs)], axis=-1)
        return jnp.einsum("dhwk,njk->ndhwj", base.astype(th.dtype), th)

    return apply_op("affine_grid", fn, theta)


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW",
                   name=None):
    """TSM channel shift across the temporal segment dim (x: [N*T, C, H, W]).

    The first ``shift_ratio`` of channels shifts backward in time (t reads
    t+1), the second forward (t reads t-1), zero padded at the ends."""

    def fn(v):
        nhwc = data_format == "NHWC"
        if nhwc:
            v = jnp.transpose(v, (0, 3, 1, 2))
        nt, c, h, w = v.shape
        n = nt // seg_num
        v5 = v.reshape(n, seg_num, c, h, w)
        fold = int(c * shift_ratio)
        pad_t = jnp.zeros((n, 1, fold, h, w), v.dtype)
        back = jnp.concatenate([v5[:, 1:, :fold], pad_t], axis=1)
        fwd = jnp.concatenate([pad_t, v5[:, :-1, fold:2 * fold]], axis=1)
        keep = v5[:, :, 2 * fold:]
        out = jnp.concatenate([back, fwd, keep], axis=2).reshape(nt, c, h, w)
        if nhwc:
            out = jnp.transpose(out, (0, 2, 3, 1))
        return out

    return apply_op("temporal_shift", fn, x)


def gather_tree(ids, parents, name=None):
    """Backtrace beam-search chains: ids/parents [max_time, batch, beam].

    out[T-1] = ids[T-1]; walking backward, out[t] follows the parent beam
    selected at t+1 (reference gather_tree_kernel.cu)."""

    def fn(ids_, par_):
        T = ids_.shape[0]
        beams = jnp.arange(ids_.shape[2])[None, :]  # tracks current beam idx
        beams = jnp.broadcast_to(beams, ids_.shape[1:])

        def step(carry, t):
            beam_idx = carry  # [batch, beam] which original beam each slot follows
            tok = jnp.take_along_axis(ids_[t], beam_idx, axis=1)
            nxt = jnp.take_along_axis(par_[t], beam_idx, axis=1)
            return nxt, tok

        _, toks = lax.scan(step, beams, jnp.arange(T - 1, -1, -1))
        return toks[::-1]

    return apply_op("gather_tree", fn, ids, parents)


def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  input_length=None, label_length=None, name=None):
    """Levenshtein distance between token sequences (padded [B, L] + lengths).

    Returns (distance [B, 1] float32, sequence_num [1]). Reference:
    edit_distance_kernel.cu; the O(L1*L2) DP runs as a lax.scan over input
    tokens carrying one DP row per batch element."""

    def compact(seq, length, ignored):
        """Drop ignored tokens, keep order, return (seq, new_length)."""
        valid = jnp.ones(seq.shape, bool)
        for t in ignored:
            valid &= seq != t
        valid &= jnp.arange(seq.shape[1])[None, :] < length[:, None]
        pos = jnp.cumsum(valid, axis=1) - 1
        # vectorized scatter: for each row, place seq[j] at pos[j] if valid
        B, L = seq.shape
        rows = jnp.repeat(jnp.arange(B)[:, None], L, 1)
        tgt = jnp.where(valid, pos, L)  # invalid -> dump slot
        buf = jnp.full((B, L + 1), -1, seq.dtype)
        buf = buf.at[rows, tgt].set(seq)
        return buf[:, :L], valid.sum(axis=1)

    def fn(a, b, alen, blen):
        alen = (alen if alen is not None
                else jnp.full((a.shape[0],), a.shape[1]))
        blen = (blen if blen is not None
                else jnp.full((b.shape[0],), b.shape[1]))
        alen = alen.reshape(-1).astype(jnp.int32)
        blen = blen.reshape(-1).astype(jnp.int32)
        aa, bb = a, b
        if ignored_tokens:
            aa, alen = compact(a, alen, ignored_tokens)
            bb, blen = compact(b, blen, ignored_tokens)
        B, L1 = aa.shape
        L2 = bb.shape[1]
        js = jnp.arange(L2 + 1)
        # DP row for prefix i of `a`: row[j] = dist(a[:i], b[:j])
        row0 = jnp.broadcast_to(js[None, :], (B, L2 + 1)).astype(jnp.float32)

        def step(row, i):
            ai = aa[:, i][:, None]                      # [B,1]
            sub = row[:, :-1] + (ai != bb).astype(jnp.float32)  # substitution
            dele = row[:, 1:] + 1.0                     # delete from a

            def inner(carry, j):
                left = carry
                best = jnp.minimum(jnp.minimum(sub[:, j], dele[:, j]),
                                   left + 1.0)
                return best, best

            first = row[:, 0] + 1.0  # dist(a[:i+1], b[:0])
            _, rest = lax.scan(inner, first, jnp.arange(L2))
            new_row = jnp.concatenate([first[:, None], rest.T], axis=1)
            # rows beyond this sequence's length keep the previous row
            keep = (i < alen)[:, None]
            return jnp.where(keep, new_row, row), None

        row, _ = lax.scan(step, row0, jnp.arange(L1))
        dist = jnp.take_along_axis(row, blen[:, None], axis=1)[:, 0]
        if normalized:
            dist = dist / jnp.maximum(blen, 1).astype(jnp.float32)
        return dist[:, None].astype(jnp.float32), jnp.array([B], jnp.int64)

    args = [input, label]
    il = input_length if input_length is not None else None
    ll = label_length if label_length is not None else None
    out = apply_op("edit_distance", fn, input, label, il, ll)
    return out


def rnnt_loss(logits, labels, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.0, reduction="mean", name=None):
    """RNN-Transducer loss (reference: warprnnt external kernel).

    logits [B, T, U+1, V] log-probs or raw (normalized internally),
    labels [B, U]. Forward-variable DP in log space:
    alpha[t,u] = logaddexp(alpha[t-1,u] + blank(t-1,u),
                           alpha[t,u-1] + emit(t,u-1)).
    Scan over t; the in-row emit recurrence scans over u."""

    def fn(lg, lb, tl, ul):
        lg = jax.nn.log_softmax(lg.astype(jnp.float32), axis=-1)
        B, T, U1, V = lg.shape
        U = U1 - 1
        blank_lp = lg[..., blank]                        # [B, T, U+1]
        emit_lp = jnp.take_along_axis(
            lg[:, :, :U, :], lb[:, None, :, None].astype(jnp.int32), axis=-1
        )[..., 0]                                        # [B, T, U]
        NEG = -1e30

        # alpha row for t: [B, U+1]
        def row_init():
            # t = 0: alpha[0,0]=0; alpha[0,u] = sum emit(0,:u)
            e0 = jnp.concatenate(
                [jnp.zeros((B, 1), jnp.float32),
                 jnp.cumsum(emit_lp[:, 0, :], axis=1)], axis=1)
            valid_u = jnp.arange(U1)[None, :] <= ul[:, None]
            return jnp.where(valid_u, e0, NEG)

        def step(alpha, t):
            # horizontal: from previous time, same u, via blank
            via_blank = alpha + blank_lp[:, t - 1, :]

            def inner(carry, u):
                left = carry  # alpha_new[t, u-1]
                stay = via_blank[:, u]
                emit = left + emit_lp[:, t, u - 1]
                a = jnp.logaddexp(stay, emit)
                return a, a

            first = via_blank[:, 0]
            _, rest = lax.scan(inner, first, jnp.arange(1, U1))
            new = jnp.concatenate([first[:, None], rest.T], axis=1)
            valid_t = (t < tl)[:, None]
            new = jnp.where(valid_t, new, alpha)
            valid_u = jnp.arange(U1)[None, :] <= ul[:, None]
            return jnp.where(valid_u, new, NEG), None

        alpha, _ = lax.scan(step, row_init(), jnp.arange(1, T))
        # final: alpha[T-1, U] + blank(T-1, U) per true lengths
        last_t = jnp.maximum(tl - 1, 0).astype(jnp.int32)
        bidx = jnp.arange(B)
        a_final = alpha[bidx, ul]                        # [B]
        lp_final = blank_lp[bidx, last_t, ul]
        nll = -(a_final + lp_final)
        if reduction == "mean":
            return jnp.mean(nll)
        if reduction == "sum":
            return jnp.sum(nll)
        return nll

    return apply_op("rnnt_loss", fn, logits, labels,
                    _as_i32(input_lengths), _as_i32(label_lengths))


def _as_i32(x):
    if isinstance(x, Tensor):
        return Tensor(x._data.astype(jnp.int32))
    return jnp.asarray(x, jnp.int32)


def class_center_sample(label, num_classes, num_samples, group=None,
                        name=None):
    """Sample ``num_samples`` class centers always containing the positives
    (reference: class_center_sample_kernel.cu, PLSC). Returns
    (remapped_label [N], sampled_class_index [num_samples]).

    Static-shape note: the output is always exactly ``num_samples`` wide
    (XLA-friendly); callers must keep the unique-positive count <=
    num_samples (the reference grows the output dynamically in that case)."""

    def fn(lb, key):
        score = jax.random.uniform(key, (num_classes,))
        # positives get score > 1 so top-k always includes them
        score = score.at[lb].set(2.0)
        _, sampled = lax.top_k(score, num_samples)
        sampled = jnp.sort(sampled)
        remapped = jnp.searchsorted(sampled, lb).astype(lb.dtype)
        return remapped, sampled.astype(lb.dtype)

    return apply_op("class_center_sample", fn, label, rng_arg())


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5, margin3=0.0,
                         scale=64.0, group=None, return_softmax=False,
                         reduction="mean", name=None):
    """ArcFace-family margin softmax CE (reference:
    margin_cross_entropy_kernel.cu): target logit cos(theta) becomes
    cos(margin1*theta + margin2) - margin3, all logits scaled by ``scale``."""

    def fn(lg, lb):
        lgf = lg.astype(jnp.float32)
        N = lg.shape[0]
        idx = jnp.arange(N)
        target = jnp.clip(lgf[idx, lb], -1.0, 1.0)
        theta = jnp.arccos(target)
        m_target = jnp.cos(margin1 * theta + margin2) - margin3
        lgm = lgf.at[idx, lb].set(m_target) * scale
        logp = jax.nn.log_softmax(lgm, axis=-1)
        nll = -logp[idx, lb]
        if reduction == "mean":
            loss = jnp.mean(nll)
        elif reduction == "sum":
            loss = jnp.sum(nll)
        else:
            loss = nll[:, None]
        if return_softmax:
            return loss, jnp.exp(logp).astype(lg.dtype)
        return loss

    return apply_op("margin_cross_entropy", fn, logits, label)
