"""Attention functionals.

Parity: paddle's scaled_dot_product_attention / flash_attention surface
(reference: python/paddle/nn/functional/flash_attention.py, kernel
paddle/phi/kernels/gpu/flash_attn_kernel.cu:128-245). TPU-native: the hot path
is a Pallas flash-attention kernel (paddle_tpu/ops/pallas/flash_attention.py);
a pure-XLA fallback covers CPU tests and odd shapes.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...autograd.engine import apply_op


def _sdpa_ref(q, k, v, mask=None, causal=False, scale=None, dropout_p=0.0, dropout_key=None):
    """Reference attention over [B, S, H, D] (paddle layout)."""
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    # [B, S, H, D] -> [B, H, S, D]
    qh = jnp.swapaxes(q, 1, 2)
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    if kh.shape[1] != qh.shape[1]:  # GQA: repeat kv heads
        rep = qh.shape[1] // kh.shape[1]
        kh = jnp.repeat(kh, rep, axis=1)
        vh = jnp.repeat(vh, rep, axis=1)
    logits = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * s
    logits = logits.astype(jnp.float32)
    if causal:
        q_len, k_len = logits.shape[-2], logits.shape[-1]
        causal_mask = jnp.tril(jnp.ones((q_len, k_len), bool), k_len - q_len)
        logits = jnp.where(causal_mask, logits, -1e30)
    if mask is not None:
        logits = logits + mask.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    if dropout_p > 0.0 and dropout_key is not None:
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout_p, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p), 0.0).astype(q.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vh)
    return jnp.swapaxes(out, 1, 2)  # back to [B, S, H, D]


def scaled_dot_product_attention(
    query,
    key,
    value,
    attn_mask=None,
    dropout_p=0.0,
    is_causal=False,
    training=True,
    name=None,
):
    """Inputs [batch, seq, heads, head_dim] (paddle layout)."""
    from ...framework.random import default_generator

    dkey = default_generator.next_key() if (dropout_p > 0.0 and training) else None
    use_flash = (
        _flash_usable(query)
        and query.shape[1] == key.shape[1]
        and query.shape[2] == key.shape[2]  # no GQA in the kernel yet
    )

    def fn(q, k, v, *rest):
        mask = rest[0] if rest else None
        if use_flash and mask is None and dkey is None:
            from ...ops.pallas.flash_attention import flash_attention

            return flash_attention(q, k, v, causal=is_causal)
        return _sdpa_ref(
            q, k, v, mask=mask, causal=is_causal,
            dropout_p=dropout_p if training else 0.0, dropout_key=dkey,
        )

    args = [query, key, value] + ([attn_mask] if attn_mask is not None else [])
    return apply_op("scaled_dot_product_attention", fn, *args)


def _flash_usable(query) -> bool:
    """Pallas flash attention needs TPU + aligned head dims."""
    import jax as _jax

    try:
        platform = _jax.devices()[0].platform
    except RuntimeError:
        return False
    if platform not in ("tpu",):
        return False
    d = query._data.shape[-1] if hasattr(query, "_data") else query.shape[-1]
    s = query._data.shape[1] if hasattr(query, "_data") else query.shape[1]
    return d % 64 == 0 and s % 128 == 0


def flash_attention(
    query, key, value, dropout=0.0, causal=False, return_softmax=False,
    fixed_seed_offset=None, rng_name="", training=True, name=None,
):
    """paddle.nn.functional.flash_attention.flash_attention parity."""
    out = scaled_dot_product_attention(
        query, key, value, dropout_p=dropout, is_causal=causal, training=training
    )
    if return_softmax:
        return out, None
    return out, None


def flash_attn_unpadded(
    query, key, value, cu_seqlens_q, cu_seqlens_k, max_seqlen_q, max_seqlen_k,
    scale=None, dropout=0.0, causal=False, return_softmax=False, training=True, name=None,
):
    """Varlen flash attention: [total_tokens, H, D] with cumulative seqlens.

    XLA fallback: segment-masked attention over the packed batch.
    """

    def fn(q, k, v, cu_q, cu_k):
        total_q = q.shape[0]
        seg_q = jnp.searchsorted(cu_q, jnp.arange(total_q), side="right") - 1
        total_k = k.shape[0]
        seg_k = jnp.searchsorted(cu_k, jnp.arange(total_k), side="right") - 1
        d = q.shape[-1]
        s = scale if scale is not None else 1.0 / math.sqrt(d)
        logits = jnp.einsum("qhd,khd->hqk", q, k) * s
        logits = logits.astype(jnp.float32)
        same = seg_q[:, None] == seg_k[None, :]
        if causal:
            pos_q = jnp.arange(total_q) - jnp.take(cu_q, seg_q)
            pos_k = jnp.arange(total_k) - jnp.take(cu_k, seg_k)
            same = same & (pos_k[None, :] <= pos_q[:, None])
        logits = jnp.where(same[None], logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
        return jnp.einsum("hqk,khd->qhd", probs, v)

    out = apply_op("flash_attn_unpadded", fn, query, key, value, cu_seqlens_q, cu_seqlens_k)
    return out, None
