"""Attention functionals.

Parity: paddle's scaled_dot_product_attention / flash_attention surface
(reference: python/paddle/nn/functional/flash_attention.py, kernel
paddle/phi/kernels/gpu/flash_attn_kernel.cu:128-245). TPU-native: the hot path
is a Pallas flash-attention kernel (paddle_tpu/ops/pallas/flash_attention.py);
a pure-XLA fallback covers CPU tests and odd shapes.
"""
from __future__ import annotations

import math

import numpy as np

import jax
import jax.numpy as jnp

from ...autograd.engine import apply_op


def _sdpa_ref(q, k, v, mask=None, causal=False, scale=None, dropout_p=0.0, dropout_key=None):
    """Reference attention over [B, S, H, D] (paddle layout)."""
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    # [B, S, H, D] -> [B, H, S, D]
    qh = jnp.swapaxes(q, 1, 2)
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    if kh.shape[1] != qh.shape[1]:  # GQA: repeat kv heads
        rep = qh.shape[1] // kh.shape[1]
        kh = jnp.repeat(kh, rep, axis=1)
        vh = jnp.repeat(vh, rep, axis=1)
    logits = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * s
    logits = logits.astype(jnp.float32)
    if causal:
        q_len, k_len = logits.shape[-2], logits.shape[-1]
        causal_mask = jnp.tril(jnp.ones((q_len, k_len), bool), k_len - q_len)
        logits = jnp.where(causal_mask, logits, -1e30)
    if mask is not None:
        logits = logits + mask.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    if dropout_p > 0.0 and dropout_key is not None:
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout_p, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p), 0.0).astype(q.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vh)
    return jnp.swapaxes(out, 1, 2)  # back to [B, S, H, D]


def scaled_dot_product_attention(
    query,
    key,
    value,
    attn_mask=None,
    dropout_p=0.0,
    is_causal=False,
    training=True,
    name=None,
    scale=None,
):
    """Inputs [batch, seq, heads, head_dim] (paddle layout)."""
    from ...framework.random import rng_arg

    with_dropout = dropout_p > 0.0 and training
    use_flash = (
        _flash_usable(query)
        and query.shape[1] == key.shape[1]
        and query.shape[2] % key.shape[2] == 0  # GQA rides the kernel
    )
    if use_flash and attn_mask is not None:
        # mask streams into the kernel block-wise only for broadcastable
        # shapes; anything else (e.g. singleton sk) takes the reference path
        from ...ops.pallas.flash_attention import mask_kernel_compatible

        ms = tuple(attn_mask.shape)
        if len(ms) == 2:
            ms = (1, 1) + ms
        elif len(ms) == 3:
            ms = (ms[0], 1) + ms[1:]
        use_flash = mask_kernel_compatible(
            ms, query.shape[0], query.shape[2], query.shape[1], key.shape[1])

    def fn(q, k, v, *rest, dkey=None):
        mask = rest[0] if rest else None
        if use_flash and dkey is None:
            from ...ops.pallas.flash_attention import flash_attention

            return flash_attention(q, k, v, causal=is_causal, scale=scale,
                                   mask=mask)
        return _sdpa_ref(
            q, k, v, mask=mask, causal=is_causal, scale=scale,
            dropout_p=dropout_p if training else 0.0, dropout_key=dkey,
        )

    args = [query, key, value] + ([attn_mask] if attn_mask is not None else [])
    kwargs = {"dkey": rng_arg()} if with_dropout else {}
    return apply_op("scaled_dot_product_attention", fn, *args, **kwargs)


def _kernel_backend_ok() -> bool:
    import jax as _jax

    try:
        return _jax.devices()[0].platform == "tpu"
    except RuntimeError:
        return False


# Below this sequence length the fused XLA softmax-attention beats the
# Pallas kernel: the S x S score block is small enough to live in VMEM and
# XLA fuses the whole attention, while the flash grid degenerates to tiny
# per-head programs dominated by launch/prologue cost (measured on v5e:
# BERT-base seq128 runs 0.55 MFU via XLA vs 0.45 via the kernel; at seq
# >= 512 the kernel wins and is mandatory for memory). Tunable via
# FLAGS_flash_attention_min_seq.
_FLASH_MIN_SEQ = 512


def _flash_min_seq() -> int:
    from ...framework import flags

    try:
        return int(flags.flag("flash_attention_min_seq"))
    except Exception:
        return _FLASH_MIN_SEQ


def _flash_usable(query) -> bool:
    """Pallas flash attention needs TPU + aligned head dims + long enough
    sequences to beat the fused XLA path (see _FLASH_MIN_SEQ)."""
    if not _kernel_backend_ok():
        return False
    d = query._data.shape[-1] if hasattr(query, "_data") else query.shape[-1]
    s = query._data.shape[1] if hasattr(query, "_data") else query.shape[1]
    return d % 64 == 0 and s % 128 == 0 and s >= _flash_min_seq()


def flash_attention(
    query, key, value, dropout=0.0, causal=False, return_softmax=False,
    fixed_seed_offset=None, rng_name="", training=True, name=None,
):
    """paddle.nn.functional.flash_attention.flash_attention parity."""
    out = scaled_dot_product_attention(
        query, key, value, dropout_p=dropout, is_causal=causal, training=training
    )
    if return_softmax:
        return out, None
    return out, None


def flash_attn_unpadded(
    query, key, value, cu_seqlens_q, cu_seqlens_k, max_seqlen_q, max_seqlen_k,
    scale=None, dropout=0.0, causal=False, return_softmax=False, training=True, name=None,
):
    """Varlen flash attention: [total_tokens, H, D] with cumulative seqlens
    (reference: FlashAttnUnpaddedKernel, flash_attn_kernel.cu:235).

    TPU-native path: scatter the packed tokens into the static padded layout
    [b, max_seqlen, H, D] (XLA wants static shapes — a true ragged kernel
    would defeat tiling), run the varlen Pallas kernel (per-batch lengths in
    SMEM; padding costs no FLOPs), gather back. Off-TPU fallback:
    segment-masked attention over the packed batch.
    """
    b = int((cu_seqlens_q.shape if hasattr(cu_seqlens_q, "shape")
             else np.shape(cu_seqlens_q))[0]) - 1
    d_head = (query._data.shape[-1] if hasattr(query, "_data")
              else query.shape[-1])
    use_kernel = (
        _kernel_backend_ok()
        and d_head % 64 == 0
        and int(max_seqlen_q) % 128 == 0
        and int(max_seqlen_k) % 128 == 0
    )

    def kernel_fn(q, k, v, cu_q, cu_k):
        from ...ops.pallas.flash_attention import flash_attention

        h, d = q.shape[-2], q.shape[-1]
        q_lens = (cu_q[1:] - cu_q[:-1]).astype(jnp.int32)
        k_lens = (cu_k[1:] - cu_k[:-1]).astype(jnp.int32)
        seg_q = jnp.searchsorted(cu_q, jnp.arange(q.shape[0]), side="right") - 1
        pos_q = jnp.arange(q.shape[0]) - jnp.take(cu_q, seg_q)
        seg_k = jnp.searchsorted(cu_k, jnp.arange(k.shape[0]), side="right") - 1
        pos_k = jnp.arange(k.shape[0]) - jnp.take(cu_k, seg_k)
        qp = jnp.zeros((b, int(max_seqlen_q), h, d), q.dtype
                       ).at[seg_q, pos_q].set(q)
        kp = jnp.zeros((b, int(max_seqlen_k), h, d), k.dtype
                       ).at[seg_k, pos_k].set(k)
        vp = jnp.zeros((b, int(max_seqlen_k), h, d), v.dtype
                       ).at[seg_k, pos_k].set(v)
        out = flash_attention(qp, kp, vp, causal=causal, scale=scale,
                              q_seqlens=q_lens, kv_seqlens=k_lens)
        return out[seg_q, pos_q]

    def fallback_fn(q, k, v, cu_q, cu_k):
        total_q = q.shape[0]
        seg_q = jnp.searchsorted(cu_q, jnp.arange(total_q), side="right") - 1
        total_k = k.shape[0]
        seg_k = jnp.searchsorted(cu_k, jnp.arange(total_k), side="right") - 1
        d = q.shape[-1]
        s = scale if scale is not None else 1.0 / math.sqrt(d)
        logits = jnp.einsum("qhd,khd->hqk", q, k) * s
        logits = logits.astype(jnp.float32)
        same = seg_q[:, None] == seg_k[None, :]
        if causal:
            pos_q = jnp.arange(total_q) - jnp.take(cu_q, seg_q)
            pos_k = jnp.arange(total_k) - jnp.take(cu_k, seg_k)
            same = same & (pos_k[None, :] <= pos_q[:, None])
        logits = jnp.where(same[None], logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
        return jnp.einsum("hqk,khd->qhd", probs, v)

    fn = kernel_fn if use_kernel else fallback_fn
    out = apply_op("flash_attn_unpadded", fn, query, key, value, cu_seqlens_q, cu_seqlens_k)
    return out, None
