"""Loss functionals (parity: python/paddle/nn/functional/loss.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...autograd.engine import apply_op
from ...tensor.tensor import Tensor


def _reduce(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def cross_entropy(
    input,
    label,
    weight=None,
    ignore_index=-100,
    reduction="mean",
    soft_label=False,
    axis=-1,
    use_softmax=True,
    label_smoothing=0.0,
    name=None,
):
    def fn(logits, lab, *rest):
        n_classes = logits.shape[axis]
        # Softmax CE never materializes log-probs: every path reduces to
        # logsumexp minus a contraction of the raw logits (for soft labels,
        # sum(soft * logp) = sum(soft * logits) - lse since sum(soft) == 1).
        # At LM vocab sizes the [N, V] logp intermediate is pure HBM traffic
        # (measured ~4 MFU points on BERT-base MLM); lse reduces in fp32.
        acc_dt = jnp.promote_types(logits.dtype, jnp.float32)
        if use_softmax:
            lse = jax.scipy.special.logsumexp(
                logits.astype(acc_dt), axis=axis)
        else:
            logp_fallback = jnp.log(jnp.clip(logits, 1e-15, 1.0))
            lse = None
        if soft_label or (lab.ndim == logits.ndim and lab.shape == logits.shape):
            soft = lab
            if label_smoothing > 0:
                soft = soft * (1 - label_smoothing) + label_smoothing / n_classes
            if use_softmax:
                dot = jnp.sum(soft.astype(acc_dt)
                              * logits.astype(acc_dt), axis=axis)
                # sum(soft * logp) = sum(soft * logits) - sum(soft) * lse;
                # the weight on lse matters when labels are unnormalized
                loss = lse * jnp.sum(soft.astype(acc_dt), axis=axis) - dot
            else:
                loss = -jnp.sum(soft * logp_fallback, axis=axis)
            loss = loss.astype(logits.dtype)
            valid = jnp.ones(loss.shape, logits.dtype)
        else:
            lab_idx = lab
            if lab_idx.ndim == logits.ndim:
                lab_idx = jnp.squeeze(lab_idx, axis=axis)
            valid = (lab_idx != ignore_index).astype(logits.dtype)
            safe = jnp.where(lab_idx == ignore_index, 0, lab_idx)
            src = logits if use_softmax else logp_fallback
            picked = jnp.take_along_axis(
                src, jnp.expand_dims(safe, axis % logits.ndim), axis=axis
            ).squeeze(axis % logits.ndim).astype(acc_dt)
            if use_softmax:
                nll = lse - picked
                if label_smoothing > 0:
                    # mean(logp) = mean(logits) - lse
                    smooth = lse - jnp.mean(
                        logits.astype(acc_dt), axis=axis)
                    nll = (1 - label_smoothing) * nll + label_smoothing * smooth
            else:
                nll = -picked
                if label_smoothing > 0:
                    nll = ((1 - label_smoothing) * nll
                           + label_smoothing * (-jnp.mean(logp_fallback, axis=axis)))
            loss = nll.astype(logits.dtype) * valid
            if rest:  # class weights
                w = jnp.take(rest[0], safe)
                loss = loss * w
                valid = valid * w
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(jnp.sum(valid), 1e-12)
        if reduction == "sum":
            return jnp.sum(loss)
        return loss

    args = [input, label] + ([weight] if weight is not None else [])
    return apply_op("cross_entropy", fn, *args)


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100, axis=-1, return_softmax=False, numeric_stable_mode=True):
    loss = cross_entropy(logits, label, soft_label=soft_label, ignore_index=ignore_index, reduction="none", axis=axis)
    from .activation import softmax as _softmax

    loss = apply_op("unsqueeze_last", lambda v: jnp.expand_dims(v, axis), loss)
    if return_softmax:
        return loss, _softmax(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    def fn(logp, lab, *rest):
        valid = (lab != ignore_index).astype(logp.dtype)
        safe = jnp.where(lab == ignore_index, 0, lab)
        if logp.ndim > 2:  # [N, C, d1...] -> move C last
            moved = jnp.moveaxis(logp, 1, -1)
        else:
            moved = logp
        picked = jnp.take_along_axis(moved, safe[..., None], axis=-1)[..., 0]
        loss = -picked * valid
        den = valid
        if rest:
            w = jnp.take(rest[0], safe)
            loss = loss * w
            den = valid * w
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(jnp.sum(den), 1e-12)
        if reduction == "sum":
            return jnp.sum(loss)
        return loss

    args = [input, label] + ([weight] if weight is not None else [])
    return apply_op("nll_loss", fn, *args)


def mse_loss(input, label, reduction="mean", name=None):
    return apply_op(
        "mse_loss", lambda a, b: _reduce(jnp.square(a - b), reduction), input, label
    )


def l1_loss(input, label, reduction="mean", name=None):
    return apply_op(
        "l1_loss", lambda a, b: _reduce(jnp.abs(a - b), reduction), input, label
    )


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def fn(a, b):
        d = a - b
        loss = jnp.where(
            jnp.abs(d) < delta, 0.5 * d * d / delta, jnp.abs(d) - 0.5 * delta
        )
        return _reduce(loss, reduction)

    return apply_op("smooth_l1_loss", fn, input, label)


def huber_loss(input, label, delta=1.0, reduction="mean", name=None):
    def fn(a, b):
        d = a - b
        loss = jnp.where(jnp.abs(d) <= delta, 0.5 * d * d, delta * (jnp.abs(d) - 0.5 * delta))
        return _reduce(loss, reduction)

    return apply_op("huber_loss", fn, input, label)


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    def fn(p, y, *rest):
        p = jnp.clip(p, 1e-12, 1 - 1e-12)
        loss = -(y * jnp.log(p) + (1 - y) * jnp.log(1 - p))
        if rest:
            loss = loss * rest[0]
        return _reduce(loss, reduction)

    args = [input, label] + ([weight] if weight is not None else [])
    return apply_op("binary_cross_entropy", fn, *args)


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean", pos_weight=None, name=None):
    has_pw, has_w = pos_weight is not None, weight is not None  # cacheable

    def fn(z, y, *rest):
        i = 0
        if has_pw:
            pw = rest[i]
            i += 1
            log_sig = jax.nn.log_sigmoid(z)
            log_one_minus = jax.nn.log_sigmoid(-z)
            loss = -(pw * y * log_sig + (1 - y) * log_one_minus)
        else:
            loss = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        if has_w:
            loss = loss * rest[i]
        return _reduce(loss, reduction)

    args = [logit, label]
    if pos_weight is not None:
        args.append(pos_weight)
    if weight is not None:
        args.append(weight)
    return apply_op("bce_with_logits", fn, *args)


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    def fn(logp, target):
        if log_target:
            loss = jnp.exp(target) * (target - logp)
        else:
            safe_t = jnp.clip(target, 1e-12, None)
            loss = target * (jnp.log(safe_t) - logp)
        if reduction == "batchmean":
            return jnp.sum(loss) / logp.shape[0]
        return _reduce(loss, reduction)

    return apply_op("kl_div", fn, input, label)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    return apply_op(
        "margin_ranking_loss",
        lambda a, b, y: _reduce(jnp.maximum(0.0, -y * (a - b) + margin), reduction),
        input,
        other,
        label,
    )


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    return apply_op(
        "hinge_embedding_loss",
        lambda x, y: _reduce(
            jnp.where(y == 1, x, jnp.maximum(0.0, margin - x)), reduction
        ),
        input,
        label,
    )


def cosine_embedding_loss(input1, input2, label, margin=0, reduction="mean", name=None):
    def fn(a, b, y):
        cos = jnp.sum(a * b, -1) / (
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1) + 1e-12
        )
        loss = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(loss, reduction)

    return apply_op("cosine_embedding_loss", fn, input1, input2, label)


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0, epsilon=1e-6, swap=False, reduction="mean", name=None):
    def fn(a, pos, neg):
        dp = jnp.sum(jnp.abs(a - pos) ** p, -1) ** (1 / p)
        dn = jnp.sum(jnp.abs(a - neg) ** p, -1) ** (1 / p)
        if swap:
            dn2 = jnp.sum(jnp.abs(pos - neg) ** p, -1) ** (1 / p)
            dn = jnp.minimum(dn, dn2)
        return _reduce(jnp.maximum(0.0, dp - dn + margin), reduction)

    return apply_op("triplet_margin_loss", fn, input, positive, negative)


def multi_label_soft_margin_loss(input, label, weight=None, reduction="mean", name=None):
    def fn(x, y, *rest):
        loss = -(y * jax.nn.log_sigmoid(x) + (1 - y) * jax.nn.log_sigmoid(-x))
        loss = jnp.mean(loss, axis=-1)
        if rest:
            loss = loss * rest[0]
        return _reduce(loss, reduction)

    args = [input, label] + ([weight] if weight is not None else [])
    return apply_op("multi_label_soft_margin_loss", fn, *args)


def soft_margin_loss(input, label, reduction="mean", name=None):
    return apply_op(
        "soft_margin_loss",
        lambda x, y: _reduce(jnp.log1p(jnp.exp(-y * x)), reduction),
        input,
        label,
    )


def square_error_cost(input, label):
    return apply_op("square_error_cost", lambda a, b: jnp.square(a - b), input, label)


def log_loss(input, label, epsilon=1e-4, name=None):
    return apply_op(
        "log_loss",
        lambda p, y: -y * jnp.log(p + epsilon) - (1 - y) * jnp.log(1 - p + epsilon),
        input,
        label,
    )


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0, reduction="sum", name=None):
    def fn(z, y, *rest):
        p = jax.nn.sigmoid(z)
        ce = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        loss = a_t * ((1 - p_t) ** gamma) * ce
        if rest:
            loss = loss / rest[0]
        return _reduce(loss, reduction)

    args = [logit, label] + ([normalizer] if normalizer is not None else [])
    return apply_op("sigmoid_focal_loss", fn, *args)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0, reduction="mean", norm_by_times=False):
    """CTC via the standard log-alpha dynamic program (lax.scan over time)."""

    def fn(lp, lab, in_len, lab_len):
        # lp: [T, N, C] log-softmax already applied by caller convention
        lp = jax.nn.log_softmax(lp, axis=-1)
        T, N, C = lp.shape
        S = lab.shape[1]
        ext = jnp.full((N, 2 * S + 1), blank, dtype=lab.dtype)
        ext = ext.at[:, 1::2].set(lab)
        ext_len = 2 * lab_len + 1
        neg_inf = -1e30
        alpha0 = jnp.full((N, 2 * S + 1), neg_inf)
        alpha0 = alpha0.at[:, 0].set(lp[0, jnp.arange(N), blank])
        alpha0 = alpha0.at[:, 1].set(
            jnp.where(S > 0, lp[0, jnp.arange(N), ext[:, 1]], neg_inf)
        )

        same = jnp.concatenate(
            [jnp.ones((N, 2), bool), ext[:, 2:] == ext[:, :-2]], axis=1
        )

        def step(alpha, lp_t):
            a1 = alpha
            a2 = jnp.concatenate([jnp.full((N, 1), neg_inf), alpha[:, :-1]], 1)
            a3 = jnp.concatenate([jnp.full((N, 2), neg_inf), alpha[:, :-2]], 1)
            a3 = jnp.where(same, neg_inf, a3)
            merged = jnp.logaddexp(jnp.logaddexp(a1, a2), a3)
            emit = jnp.take_along_axis(lp_t, ext, axis=1)
            return merged + emit, None

        alpha, _ = jax.lax.scan(step, alpha0, lp[1:])
        # alpha at each sequence's final time step: handle variable input_lengths
        def gather_final(alpha_all, t_idx, n):
            return alpha_all

        # rescan retaining per-step alphas for variable lengths
        def step2(carry, lp_t):
            alpha, t = carry
            a1 = alpha
            a2 = jnp.concatenate([jnp.full((N, 1), neg_inf), alpha[:, :-1]], 1)
            a3 = jnp.concatenate([jnp.full((N, 2), neg_inf), alpha[:, :-2]], 1)
            a3 = jnp.where(same, neg_inf, a3)
            merged = jnp.logaddexp(jnp.logaddexp(a1, a2), a3)
            emit = jnp.take_along_axis(lp_t, ext, axis=1)
            new_alpha = merged + emit
            return (new_alpha, t + 1), new_alpha

        (alphaT, _), alphas = jax.lax.scan(step2, (alpha0, 1), lp[1:])
        all_alphas = jnp.concatenate([alpha0[None], alphas], 0)  # [T, N, 2S+1]
        t_final = jnp.clip(in_len - 1, 0, T - 1)
        final = all_alphas[t_final, jnp.arange(N)]  # [N, 2S+1]
        idx_last = jnp.clip(ext_len - 1, 0, 2 * S)
        idx_prev = jnp.clip(ext_len - 2, 0, 2 * S)
        ll = jnp.logaddexp(
            jnp.take_along_axis(final, idx_last[:, None], 1)[:, 0],
            jnp.take_along_axis(final, idx_prev[:, None], 1)[:, 0],
        )
        loss = -ll
        if norm_by_times:
            loss = loss / in_len.astype(loss.dtype)
        if reduction == "mean":
            return jnp.mean(loss / lab_len.astype(loss.dtype))
        if reduction == "sum":
            return jnp.sum(loss)
        return loss

    return apply_op("ctc_loss", fn, log_probs, labels, input_lengths, label_lengths)


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean", name=None):
    """Gaussian negative log likelihood (reference nn/functional/loss.py
    gaussian_nll_loss over phi): 0.5*(log(max(var,eps)) + (x-y)^2/max(var,
    eps)), + 0.5*log(2*pi) when full."""
    import math

    def fn(x, y, var):
        v = jnp.clip(var, epsilon)
        loss = 0.5 * (jnp.log(v) + jnp.square(x - y) / v)
        if full:
            loss = loss + 0.5 * math.log(2 * math.pi)
        return _reduce(loss, reduction)

    return apply_op("gaussian_nll_loss", fn, input, label, variance)


def poisson_nll_loss(input, label, log_input=True, full=False,
                     epsilon=1e-8, reduction="mean", name=None):
    """Poisson NLL (reference loss.py poisson_nll_loss): exp(x) - y*x when
    log_input else x - y*log(x+eps); Stirling term for y > 1 when full."""

    def fn(x, y):
        if log_input:
            loss = jnp.exp(x) - y * x
        else:
            loss = x - y * jnp.log(x + epsilon)
        if full:
            stirling = y * jnp.log(y) - y + 0.5 * jnp.log(2 * jnp.pi * y)
            loss = loss + jnp.where(y > 1, stirling, 0.0)
        return _reduce(loss, reduction)

    return apply_op("poisson_nll_loss", fn, input, label)


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean", name=None):
    """Multi-class margin loss (reference loss.py multi_margin_loss):
    mean_j!=y max(0, margin - x_y + x_j)^p / C, optionally scaled by
    weight[y]."""

    def fn(x, y, *rest):
        n, c = x.shape
        xy = jnp.take_along_axis(x, y[:, None], axis=1)  # [n, 1]
        m = jnp.maximum(0.0, margin - xy + x) ** p
        m = m * (1.0 - jax.nn.one_hot(y, c, dtype=x.dtype))  # drop j == y
        if rest:
            m = m * rest[0][y][:, None]
        return _reduce(jnp.sum(m, axis=1) / c, reduction)

    args = [input, label] + ([weight] if weight is not None else [])
    return apply_op("multi_margin_loss", fn, *args)


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean",
                                      name=None):
    """Triplet loss with a user distance (reference loss.py
    triplet_margin_with_distance_loss; default distance = pairwise L2)."""
    dist = distance_function or (
        lambda a, b: jnp.sqrt(jnp.clip(jnp.sum(jnp.square(a - b), -1),
                                       1e-12)))

    def fn(a, pos, neg):
        dp = dist(a, pos)
        dn = dist(a, neg)
        if swap:
            dn = jnp.minimum(dn, dist(pos, neg))
        return _reduce(jnp.maximum(0.0, dp - dn + margin), reduction)

    return apply_op("triplet_margin_with_distance_loss", fn, input,
                    positive, negative)


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid loss (reference loss.py hsigmoid_loss over
    phi/kernels/funcs/matrix_bit_code.h SimpleCode): default complete
    binary tree — class c encodes as c + num_classes, internal node for
    bit j is (code >> (j+1)) - 1, the binary target is bit j of the code;
    per-sample loss = sum over the path of BCE-with-logits. Custom trees
    ride (path_table, path_code). Returns [N, 1] like the reference."""
    import numpy as np

    C = int(num_classes)
    max_len = int(np.ceil(np.log2(max(C, 2)))) + 1

    def fn(x, y, w, *rest):
        b = rest[0] if rest else None
        code = y.astype(jnp.int32) + C
        # length = position of the leading 1 (floor(log2(code)))
        lengths = jnp.floor(
            jnp.log2(code.astype(jnp.float32) + 0.5)).astype(jnp.int32)
        # accumulate at the INPUT precision when it exceeds fp32 — an fp32
        # accumulator under float64 inputs truncates the forward enough to
        # fail finite-difference gradient checks (~1e-3 relative)
        acc_dt = jnp.float64 if x.dtype == jnp.float64 else jnp.float32
        total = jnp.zeros(x.shape[0], acc_dt)
        for j in range(max_len):
            active = j < lengths
            idx = jnp.clip((code >> (j + 1)) - 1, 0, w.shape[0] - 1)
            bit = ((code >> j) & 1).astype(jnp.float32)
            logit = jnp.sum(x * w[idx], axis=-1)
            if b is not None:
                logit = logit + b[idx]
            # BCE with logits on target=bit: softplus(logit) - bit*logit
            loss_j = jax.nn.softplus(logit) - bit * logit
            total = total + jnp.where(active, loss_j.astype(acc_dt), 0.0)
        return total[:, None]

    def fn_custom(x, table, code_bits, w, *rest):
        b = rest[0] if rest else None
        valid = table >= 0
        idx = jnp.clip(table, 0, w.shape[0] - 1)
        logit = jnp.einsum("nd,nld->nl", x, w[idx])
        if b is not None:
            logit = logit + b[idx]
        bit = code_bits.astype(logit.dtype)
        loss = jax.nn.softplus(logit) - bit * logit
        return jnp.sum(jnp.where(valid, loss, 0.0), axis=1)[:, None]

    if path_table is not None and path_code is not None:
        args = [input, path_table, path_code, weight]
        args += [bias] if bias is not None else []
        return apply_op("hsigmoid_loss", fn_custom, *args)
    args = [input, label, weight] + ([bias] if bias is not None else [])
    return apply_op("hsigmoid_loss", fn, *args)


def dice_loss(input, label, epsilon=0.00001, name=None):
    """Dice loss over sigmoid/softmax predictions vs integer labels
    (reference nn/functional/loss.py:39): 1 - 2*intersection/total, averaged
    over the batch."""
    if len(input.shape) < 2 or len(input.shape) != len(label.shape):
        raise ValueError(
            "dice_loss: input rank must be >= 2 and match label rank, got "
            f"{len(input.shape)} vs {len(label.shape)}")
    if label.shape[-1] != 1:
        raise ValueError("dice_loss: label's last dim must be 1")
    n_classes = int(input.shape[-1])
    axes = tuple(range(1, len(input.shape)))

    def fn(p, y):
        onehot = jax.nn.one_hot(jnp.squeeze(y, -1), n_classes, dtype=p.dtype)
        inter = jnp.sum(p * onehot, axis=axes)
        denom = jnp.sum(p, axis=axes) + jnp.sum(onehot, axis=axes)
        return jnp.mean(1 - 2 * inter / (denom + epsilon))

    return apply_op("dice_loss", fn, input, label)


def npair_loss(anchor, positive, labels, l2_reg=0.002, name=None):
    """N-pair loss (reference nn/functional/loss.py:305): softmax CE over
    anchor@positive.T similarities with label-equality targets, plus an L2
    term on the embeddings."""
    def fn(a, p, y):
        n = y.shape[0]
        eq = (y[:, None] == y[None, :]).astype(a.dtype)
        targets = eq / jnp.sum(eq, axis=1, keepdims=True)
        l2 = (jnp.mean(jnp.sum(jnp.square(a), 1))
              + jnp.mean(jnp.sum(jnp.square(p), 1))) * l2_reg * 0.25
        sim = a @ p.T
        logp = jax.nn.log_softmax(sim, axis=1)
        ce = jnp.mean(jnp.sum(-targets * logp, axis=1))
        return ce + l2

    return apply_op("npair_loss", fn, anchor, positive, labels)
