"""Normalization functionals (parity: python/paddle/nn/functional/norm.py).

Stats are computed in float32 regardless of input dtype (bf16-safe on TPU),
then cast back — the same accumulation-dtype discipline the reference's fused
kernels use.
"""
from __future__ import annotations

import jax.numpy as jnp

from ...autograd.engine import apply_op
from ...tensor.tensor import Tensor


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-05, name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    n_axes = len(list(normalized_shape))
    # close over BOOLEANS, not the weight/bias Tensors: a Tensor in a closure
    # cell disables the eager executable cache (mutation hazard), which made
    # every eager layer_norm pay full uncached dispatch (~4 ms vs 125 us
    # through the tunnel, BENCH_OPS r5); the values themselves flow via rest
    has_w, has_b = weight is not None, bias is not None

    def fn(v, *rest):
        axes = tuple(range(v.ndim - n_axes, v.ndim))
        x32 = v.astype(jnp.float32)
        mean = jnp.mean(x32, axis=axes, keepdims=True)
        var = jnp.mean(jnp.square(x32 - mean), axis=axes, keepdims=True)
        out = (x32 - mean) / jnp.sqrt(var + epsilon)
        out = out.astype(v.dtype)
        i = 0
        if has_w:
            out = out * rest[i]
            i += 1
        if has_b:
            out = out + rest[i]
        return out

    args = [x] + [t for t in (weight, bias) if t is not None]
    return apply_op("layer_norm", fn, *args)


def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    def fn(v, *rest):
        x32 = v.astype(jnp.float32)
        var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
        out = (x32 * jnp.reciprocal(jnp.sqrt(var + epsilon))).astype(v.dtype)
        if rest:
            out = out * rest[0]
        return out

    args = [x] + ([weight] if weight is not None else [])
    return apply_op("rms_norm", fn, *args)


def batch_norm(
    x,
    running_mean,
    running_var,
    weight=None,
    bias=None,
    training=False,
    momentum=0.9,
    epsilon=1e-05,
    data_format="NCHW",
    use_global_stats=None,
    name=None,
):
    channel_axis = 1 if data_format.startswith("NC") else x._data.ndim - 1
    use_batch_stats = training and not use_global_stats
    has_w, has_b = weight is not None, bias is not None  # cacheable closure

    def fn(v, rm, rv, *rest):
        axes = tuple(i for i in range(v.ndim) if i != channel_axis)
        shape = [1] * v.ndim
        shape[channel_axis] = v.shape[channel_axis]
        x32 = v.astype(jnp.float32)
        if use_batch_stats:
            mean = jnp.mean(x32, axis=axes)
            var = jnp.var(x32, axis=axes)
        else:
            mean, var = rm.astype(jnp.float32), rv.astype(jnp.float32)
        out = (x32 - mean.reshape(shape)) / jnp.sqrt(var.reshape(shape) + epsilon)
        out = out.astype(v.dtype)
        i = 0
        if has_w:
            out = out * rest[i].reshape(shape)
            i += 1
        if has_b:
            out = out + rest[i].reshape(shape)
        return out, mean, var

    args = [x, running_mean, running_var] + [t for t in (weight, bias) if t is not None]
    out, batch_mean, batch_var = apply_op("batch_norm", fn, *args)

    if use_batch_stats:
        # update running stats (functional rebind, momentum convention:
        # running = momentum * running + (1 - momentum) * batch)
        n = x._data.size // x._data.shape[channel_axis]
        unbiased = batch_var._data * (n / max(n - 1, 1))
        running_mean._data = (
            momentum * running_mean._data + (1 - momentum) * batch_mean._data
        ).astype(running_mean._data.dtype)
        running_var._data = (
            momentum * running_var._data + (1 - momentum) * unbiased
        ).astype(running_var._data.dtype)
    return out


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None, use_input_stats=True, momentum=0.9, eps=1e-05, data_format="NCHW", name=None):
    has_w, has_b = weight is not None, bias is not None  # cacheable closure

    def fn(v, *rest):
        axes = tuple(range(2, v.ndim))
        x32 = v.astype(jnp.float32)
        mean = jnp.mean(x32, axis=axes, keepdims=True)
        var = jnp.var(x32, axis=axes, keepdims=True)
        out = ((x32 - mean) / jnp.sqrt(var + eps)).astype(v.dtype)
        shape = [1, v.shape[1]] + [1] * (v.ndim - 2)
        i = 0
        if has_w:
            out = out * rest[i].reshape(shape)
            i += 1
        if has_b:
            out = out + rest[i].reshape(shape)
        return out

    args = [x] + [t for t in (weight, bias) if t is not None]
    return apply_op("instance_norm", fn, *args)


def group_norm(x, num_groups, epsilon=1e-05, weight=None, bias=None, data_format="NCHW", name=None):
    has_w, has_b = weight is not None, bias is not None  # cacheable closure

    def fn(v, *rest):
        if data_format == "NCHW" or v.ndim == 2:
            n, c = v.shape[0], v.shape[1]
            spatial = v.shape[2:]
            g = v.reshape(n, num_groups, c // num_groups, *spatial)
            axes = tuple(range(2, g.ndim))
            x32 = g.astype(jnp.float32)
            mean = jnp.mean(x32, axis=axes, keepdims=True)
            var = jnp.var(x32, axis=axes, keepdims=True)
            out = ((x32 - mean) / jnp.sqrt(var + epsilon)).astype(v.dtype).reshape(v.shape)
            shape = [1, c] + [1] * len(spatial)
        else:  # NHWC
            n, c = v.shape[0], v.shape[-1]
            spatial = v.shape[1:-1]
            g = v.reshape(n, *spatial, num_groups, c // num_groups)
            axes = tuple(range(1, g.ndim - 2)) + (g.ndim - 1,)
            x32 = g.astype(jnp.float32)
            mean = jnp.mean(x32, axis=axes, keepdims=True)
            var = jnp.var(x32, axis=axes, keepdims=True)
            out = ((x32 - mean) / jnp.sqrt(var + epsilon)).astype(v.dtype).reshape(v.shape)
            shape = [1] * (v.ndim - 1) + [c]
        i = 0
        if has_w:
            out = out * rest[i].reshape(shape)
            i += 1
        if has_b:
            out = out + rest[i].reshape(shape)
        return out

    args = [x] + [t for t in (weight, bias) if t is not None]
    return apply_op("group_norm", fn, *args)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW", name=None):
    def fn(v):
        c_ax = 1 if data_format.startswith("NC") else v.ndim - 1
        sq = jnp.square(v)
        half = size // 2
        moved = jnp.moveaxis(sq, c_ax, -1)
        padded = jnp.pad(moved, [(0, 0)] * (moved.ndim - 1) + [(half, size - half - 1)])
        windows = jnp.stack([padded[..., i : i + moved.shape[-1]] for i in range(size)], -1)
        summed = jnp.sum(windows, axis=-1)
        div = jnp.power(k + alpha * summed / size, beta)
        return v / jnp.moveaxis(div, -1, c_ax)

    return apply_op("local_response_norm", fn, x)
