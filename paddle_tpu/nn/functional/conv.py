"""Convolution functionals via lax.conv_general_dilated (MXU-friendly).

Parity: python/paddle/nn/functional/conv.py. Paddle weight layout is
[out_c, in_c/groups, *kernel]; data layouts NCHW (default) or NHWC. On TPU,
XLA lowers conv_general_dilated directly onto the MXU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...autograd.engine import apply_op


def _tuple(v, n):
    if isinstance(v, int):
        return (v,) * n
    return tuple(v)


def _padding(padding, n):
    if isinstance(padding, str):
        return padding.upper()  # SAME / VALID
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n and all(isinstance(p, int) for p in padding):
        return [(p, p) for p in padding]
    if len(padding) == 2 * n:
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(n)]
    return [tuple(p) for p in padding]


def _conv(x, weight, bias, stride, padding, dilation, groups, n, channel_last, name):
    stride = _tuple(stride, n)
    dilation = _tuple(dilation, n)
    pad = _padding(padding, n)
    if channel_last:
        lhs_spec = "N" + "DHW"[3 - n :] + "C"
    else:
        lhs_spec = "NC" + "DHW"[3 - n :]
    out_spec = lhs_spec
    rhs_spec = "OI" + "DHW"[3 - n :]
    dn = (lhs_spec, rhs_spec, out_spec)

    def fn(v, w, *rest):
        out = jax.lax.conv_general_dilated(
            v,
            w,
            window_strides=stride,
            padding=pad,
            rhs_dilation=dilation,
            dimension_numbers=dn,
            feature_group_count=groups,
            preferred_element_type=None,
        )
        if rest:
            b = rest[0]
            shape = [1] * out.ndim
            shape[lhs_spec.index("C")] = b.shape[0]
            out = out + b.reshape(shape)
        return out

    args = (x, weight) + ((bias,) if bias is not None else ())
    return apply_op(name, fn, *args)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCL", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1, data_format == "NLC", "conv1d")


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2, data_format == "NHWC", "conv2d")


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3, data_format == "NDHWC", "conv3d")


def _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation, groups, n, channel_last, output_size, name):
    stride = _tuple(stride, n)
    dilation = _tuple(dilation, n)
    out_pad = _tuple(output_padding, n) if output_padding is not None else (0,) * n
    if isinstance(padding, str):
        raise NotImplementedError("string padding for conv_transpose")
    pad = _padding(padding, n)
    if channel_last:
        lhs_spec = "N" + "DHW"[3 - n :] + "C"
    else:
        lhs_spec = "NC" + "DHW"[3 - n :]
    rhs_spec = "IO" + "DHW"[3 - n :]  # paddle transpose-conv weight is [in_c, out_c/groups, *k]
    dn = (lhs_spec, rhs_spec, lhs_spec)

    def fn(v, w, *rest):
        # Gradient-of-conv formulation: lhs_dilation implements the stride.
        k_eff = [dilation[i] * (w.shape[2 + i] - 1) + 1 for i in range(n)]
        trans_pad = [
            (k_eff[i] - 1 - pad[i][0], k_eff[i] - 1 - pad[i][1] + out_pad[i])
            for i in range(n)
        ]
        if groups > 1:
            # jax transposed conv with groups: reshape weight [I, O/g, ...] ->
            # batch groups along O
            pass
        w_flipped = jnp.flip(w, axis=tuple(range(2, 2 + n)))
        # swap I/O for the flipped-kernel correlation form
        out = jax.lax.conv_general_dilated(
            v,
            jnp.swapaxes(w_flipped, 0, 1) if groups == 1 else w_flipped.reshape(
                groups, w.shape[0] // groups, *w.shape[1:]
            ).swapaxes(1, 2).reshape(w.shape[1] * groups, w.shape[0] // groups, *w.shape[2:]),
            window_strides=(1,) * n,
            padding=trans_pad,
            lhs_dilation=stride,
            rhs_dilation=dilation,
            dimension_numbers=(lhs_spec, "OI" + "DHW"[3 - n :], lhs_spec),
            feature_group_count=groups,
        )
        if rest:
            b = rest[0]
            shape = [1] * out.ndim
            shape[lhs_spec.index("C")] = b.shape[0]
            out = out + b.reshape(shape)
        return out

    args = (x, weight) + ((bias,) if bias is not None else ())
    return apply_op(name, fn, *args)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0, groups=1, dilation=1, output_size=None, data_format="NCL", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation, groups, 1, data_format == "NLC", output_size, "conv1d_transpose")


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0, groups=1, dilation=1, output_size=None, data_format="NCHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation, groups, 2, data_format == "NHWC", output_size, "conv2d_transpose")


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0, groups=1, dilation=1, output_size=None, data_format="NCDHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation, groups, 3, data_format == "NDHWC", output_size, "conv3d_transpose")
