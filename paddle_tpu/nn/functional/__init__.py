"""paddle.nn.functional parity namespace."""
from .activation import *  # noqa: F401,F403
from .attention import (  # noqa: F401
    flash_attention,
    flash_attn_unpadded,
    scaled_dot_product_attention,
)
from .common import *  # noqa: F401,F403
from .conv import (  # noqa: F401
    conv1d,
    conv1d_transpose,
    conv2d,
    conv2d_transpose,
    conv3d,
    conv3d_transpose,
)
from .loss import *  # noqa: F401,F403
from .norm import (  # noqa: F401
    batch_norm,
    group_norm,
    instance_norm,
    layer_norm,
    local_response_norm,
    rms_norm,
)
from .pooling import *  # noqa: F401,F403
from .extension import (  # noqa: F401
    affine_grid,
    class_center_sample,
    edit_distance,
    gather_tree,
    margin_cross_entropy,
    rnnt_loss,
    sequence_mask,
    temporal_shift,
)

from .common import pairwise_distance, pdist  # noqa: F401
from .activation import hardtanh_, leaky_relu_, thresholded_relu_  # noqa: F401
from .loss import dice_loss, npair_loss  # noqa: F401


def sparse_attention(query, key, value, sparse_csr_offset, sparse_csr_columns,
                     key_padding_mask=None, attn_mask=None, name=None):
    """Block-sparse attention with an explicit CSR pattern (reference
    nn/functional/sparse_attention.py:19). Routed to the sparse package's
    attention kernel; offset/columns describe one [B*H*L] CSR batch."""
    from ...sparse import SparseCsrTensor
    from ...tensor.tensor import Tensor as _T
    import jax.numpy as _jnp

    B, H, L, D = (int(s) for s in query.shape)
    crows = sparse_csr_offset if isinstance(sparse_csr_offset, _T) else _T(sparse_csr_offset)
    cols = sparse_csr_columns if isinstance(sparse_csr_columns, _T) else _T(sparse_csr_columns)
    # reference passes [B, H, L+1]/[B, H, nnz]; flatten to the one-batch form
    if crows._data.ndim == 3:
        nnz_per = crows._data[:, :, -1]
        base = _jnp.cumsum(nnz_per.reshape(-1)) - nnz_per.reshape(-1)
        crows_flat = (crows._data.reshape(B * H, -1)[:, :-1]
                      + base[:, None]).reshape(-1)
        crows_flat = _jnp.append(crows_flat, base[-1] + nnz_per.reshape(-1)[-1])
        cols_flat = cols._data.reshape(-1)
        crows, cols = _T(crows_flat), _T(cols_flat)
    vals = _T(_jnp.ones(cols._data.shape, query._data.dtype))
    pattern = SparseCsrTensor(crows, cols, vals, [B * H * L, L])
    from ...sparse.nn.functional import attention as _sp_attn

    return _sp_attn(query, key, value, pattern,
                    key_padding_mask=key_padding_mask, attn_mask=attn_mask)
