"""paddle.nn.functional parity namespace."""
from .activation import *  # noqa: F401,F403
from .attention import (  # noqa: F401
    flash_attention,
    flash_attn_unpadded,
    scaled_dot_product_attention,
)
from .common import *  # noqa: F401,F403
from .conv import (  # noqa: F401
    conv1d,
    conv1d_transpose,
    conv2d,
    conv2d_transpose,
    conv3d,
    conv3d_transpose,
)
from .loss import *  # noqa: F401,F403
from .norm import (  # noqa: F401
    batch_norm,
    group_norm,
    instance_norm,
    layer_norm,
    local_response_norm,
    rms_norm,
)
from .pooling import *  # noqa: F401,F403
from .extension import (  # noqa: F401
    affine_grid,
    class_center_sample,
    edit_distance,
    gather_tree,
    margin_cross_entropy,
    rnnt_loss,
    sequence_mask,
    temporal_shift,
)
