"""Gradient clipping (parity: python/paddle/nn/clip.py).

ClipGradByGlobalNorm computes the global norm with a single fused jit'd
reduction over the whole grad pytree (one XLA program, not per-tensor ops).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..tensor.tensor import Tensor


class ClipGradBase:
    def __call__(self, params_grads):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            out.append((p, Tensor(jnp.clip(g._data, self.min, self.max))))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            norm = jnp.sqrt(jnp.sum(jnp.square(g._data.astype(jnp.float32))))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            out.append((p, Tensor((g._data * scale).astype(g._data.dtype))))
        return out


@jax.jit
def _global_norm_scale(grads_flat, clip_norm):
    sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in grads_flat)
    gnorm = jnp.sqrt(sq)
    return jnp.where(gnorm > clip_norm, clip_norm / jnp.maximum(gnorm, 1e-12), 1.0)


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group", auto_skip_clip=False):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def __call__(self, params_grads):
        grads = [g._data for p, g in params_grads if g is not None]
        if not grads:
            return params_grads
        scale = _global_norm_scale(grads, self.clip_norm)
        out = []
        for p, g in params_grads:
            if g is None or (hasattr(p, "need_clip") and p.need_clip is False):
                out.append((p, g))
            else:
                out.append((p, Tensor((g._data * scale).astype(g._data.dtype))))
        return out


def clip_grad_norm_(parameters, max_norm, norm_type=2.0, error_if_nonfinite=False):
    """torch-style utility paddle also ships (nn/utils/clip_grad_norm_.py)."""
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    grads = [p.grad._data for p in parameters if p.grad is not None]
    if not grads:
        return Tensor(jnp.zeros(()))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g)) for g in grads]))
    else:
        total = jnp.sum(jnp.stack([jnp.sum(jnp.abs(g) ** norm_type) for g in grads])) ** (
            1.0 / norm_type
        )
    scale = jnp.minimum(max_norm / (total + 1e-6), 1.0)
    for p in parameters:
        if p.grad is not None:
            p.grad._data = (p.grad._data * scale).astype(p.grad._data.dtype)
    return Tensor(total)


def clip_grad_value_(parameters, clip_value):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    for p in parameters:
        if p.grad is not None:
            p.grad._data = jnp.clip(p.grad._data, -clip_value, clip_value)
