"""paddle.nn.utils parity: grad clipping helpers, parameter vectorization,
weight/spectral norm.

Reference: python/paddle/nn/utils/{clip_grad_norm_.py,
clip_grad_value_.py, transform_parameters.py, weight_norm_hook.py,
spectral_norm_hook.py}.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..autograd.grad_mode import no_grad
from ..tensor.tensor import Tensor


@no_grad()
def clip_grad_norm_(parameters, max_norm: float, norm_type: float = 2.0,
                    error_if_nonfinite: bool = False):
    """In-place global-norm gradient clip; returns the total norm."""
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    grads = [p.grad for p in parameters if p.grad is not None]
    if not grads:
        return Tensor(jnp.zeros((), jnp.float32))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack(
            [jnp.abs(g._data).max() for g in grads]))
    else:
        total = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(g._data.astype(jnp.float32)) ** norm_type)
             for g in grads])) ** (1.0 / norm_type)
    if error_if_nonfinite and not bool(jnp.isfinite(total)):
        raise RuntimeError(
            f"the total norm of {norm_type}-order is non-finite")
    scale = jnp.clip(max_norm / (total + 1e-6), a_max=1.0)
    for g in grads:
        g._data = (g._data.astype(jnp.float32) * scale).astype(g._data.dtype)
    return Tensor(total)


@no_grad()
def clip_grad_value_(parameters, clip_value: float):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    for p in parameters:
        if p.grad is not None:
            p.grad._data = jnp.clip(p.grad._data, -clip_value, clip_value)


@no_grad()
def parameters_to_vector(parameters, name=None) -> Tensor:
    return Tensor(jnp.concatenate(
        [p._data.reshape(-1) for p in parameters]))


@no_grad()
def vector_to_parameters(vec: Tensor, parameters, name=None):
    offset = 0
    for p in parameters:
        n = int(p._data.size)
        p._data = vec._data[offset: offset + n].reshape(p._data.shape).astype(
            p._data.dtype)
        offset += n


def _l2_normalize(v, eps=1e-12):
    return v / (jnp.linalg.norm(v) + eps)


def weight_norm(layer, name: str = "weight", dim: int = 0):
    """Reparametrize ``layer.<name>`` as g * v/||v|| (reference
    weight_norm_hook). Adds <name>_g and <name>_v parameters and a
    pre-forward hook recomputing the weight."""
    from .layer.layers import Layer

    assert isinstance(layer, Layer)
    w = getattr(layer, name)
    axes = tuple(i for i in range(w._data.ndim) if i != dim)
    g0 = jnp.sqrt(jnp.sum(jnp.square(w._data.astype(jnp.float32)),
                          axis=axes, keepdims=True))
    from ..tensor.tensor import Parameter

    g = Parameter(g0.astype(w._data.dtype), name=f"{w.name}_g")
    v = Parameter(w._data, name=f"{w.name}_v")
    layer.add_parameter(f"{name}_g", g)
    layer.add_parameter(f"{name}_v", v)
    if name in layer._parameters:
        del layer._parameters[name]

    def recompute(l, inputs):
        from ..autograd.engine import apply_op

        def fn(gd, vd):
            norm = jnp.sqrt(jnp.sum(
                jnp.square(vd.astype(jnp.float32)), axis=axes,
                keepdims=True)) + 1e-12
            return (vd.astype(jnp.float32) / norm * gd.astype(jnp.float32)
                    ).astype(vd.dtype)

        setattr(l, name, apply_op("weight_norm", fn, g, v))
        return inputs

    handle = layer.register_forward_pre_hook(recompute)
    layer._weight_norm_hook = handle
    recompute(layer, ())
    return layer


def remove_weight_norm(layer, name: str = "weight"):
    handle = getattr(layer, "_weight_norm_hook", None)
    if handle is not None:
        handle.remove()
    w = getattr(layer, name)
    from ..tensor.tensor import Parameter

    layer.add_parameter(name, Parameter(w._data, name=name))
    for suffix in ("_g", "_v"):
        layer._parameters.pop(f"{name}{suffix}", None)
    return layer


def spectral_norm(layer, name: str = "weight", n_power_iterations: int = 1,
                  eps: float = 1e-12, dim: int | None = None):
    """Reparametrize weight as W / sigma_max(W), sigma estimated by power
    iteration (reference spectral_norm_hook)."""
    from .layer.layers import Layer

    assert isinstance(layer, Layer)
    w = getattr(layer, name)
    if dim is None:
        dim = 0
    wm = jnp.moveaxis(w._data, dim, 0).reshape(w._data.shape[dim], -1)
    import numpy as np

    rng = np.random.RandomState(0)
    state = {
        "u": _l2_normalize(jnp.asarray(
            rng.randn(wm.shape[0]), jnp.float32)),
    }

    def recompute(l, inputs):
        from ..autograd.engine import apply_op

        wt = getattr(l, f"{name}_orig")

        def fn(wd):
            m = jnp.moveaxis(wd.astype(jnp.float32), dim, 0)
            m2 = m.reshape(m.shape[0], -1)
            u = state["u"]
            for _ in range(n_power_iterations):
                v = _l2_normalize(m2.T @ u, eps)
                u = _l2_normalize(m2 @ v, eps)
            sigma = u @ (m2 @ v)
            return (wd.astype(jnp.float32) / sigma).astype(wd.dtype)

        setattr(l, name, apply_op("spectral_norm", fn, wt))
        return inputs

    from ..tensor.tensor import Parameter

    layer.add_parameter(f"{name}_orig", Parameter(w._data,
                                                  name=f"{w.name}_orig"))
    if name in layer._parameters:
        del layer._parameters[name]
    handle = layer.register_forward_pre_hook(recompute)
    layer._spectral_norm_hook = handle
    recompute(layer, ())
    return layer


__all__ = ["clip_grad_norm_", "clip_grad_value_", "parameters_to_vector",
           "vector_to_parameters", "weight_norm", "remove_weight_norm",
           "spectral_norm"]
