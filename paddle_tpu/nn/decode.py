"""Seq2seq decoding: BeamSearchDecoder + dynamic_decode (reference
python/paddle/nn/decode.py — the rnn.py re-exports). Eager host loop over a
step-jittable cell, mirroring the reference's while-loop semantics."""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..tensor.manipulation import concat, gather, reshape, stack
from ..tensor.tensor import Tensor


class Decoder:
    """Decoder protocol (reference decode.py Decoder): initialize/step/
    finalize over a time loop driven by dynamic_decode."""

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states, **kwargs):
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        return outputs, final_states

    @property
    def tracks_own_finished(self):
        return False


class BeamSearchDecoder(Decoder):
    """Beam-search decoding over an RNN cell (reference decode.py
    BeamSearchDecoder): expands each batch item to ``beam_size`` hypotheses,
    advances all beams through the cell, and keeps the top-k continuations
    by accumulated log-probability; finished beams absorb with their score
    frozen.
    """

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    # --- helpers over [batch * beam, ...] flat layout ---
    def _merge(self, t):
        return reshape(t, [-1] + list(t.shape[2:]))

    def _split(self, t, batch):
        return reshape(t, [batch, self.beam_size] + list(t.shape[1:]))

    def _tile_beam(self, t):
        """[batch, ...] -> [batch * beam, ...] (tile_beam_merge_with_batch)."""
        data = jnp.repeat(t._data[:, None], self.beam_size, axis=1)
        return Tensor(data.reshape((-1,) + t._data.shape[1:]))

    tile_beam_merge_with_batch = _tile_beam

    def initialize(self, initial_cell_states):
        states = [self._tile_beam(s) for s in _as_list(initial_cell_states)]
        batch = states[0].shape[0] // self.beam_size
        ids = np.full((batch * self.beam_size,), self.start_token, np.int64)
        # only beam 0 is live at t=0 (others -inf so duplicates don't win)
        logp = np.full((batch, self.beam_size), -1e9, np.float32)
        logp[:, 0] = 0.0
        init = {
            "log_probs": Tensor(jnp.asarray(logp)),
            "finished": Tensor(jnp.zeros((batch, self.beam_size), jnp.bool_)),
            "lengths": Tensor(jnp.zeros((batch, self.beam_size), jnp.int64)),
            "cell_states": states,
        }
        return Tensor(jnp.asarray(ids)), init, init["finished"]

    def step(self, time, inputs, states, **kwargs):
        batch = states["log_probs"].shape[0]
        if self.embedding_fn is not None:
            inputs = self.embedding_fn(inputs)
        cell_out, next_cell_states = self.cell(inputs, states["cell_states"],
                                               **kwargs)
        if self.output_fn is not None:
            cell_out = self.output_fn(cell_out)
        vocab = int(cell_out.shape[-1])
        import jax

        logits = cell_out._data.reshape(batch, self.beam_size, vocab)
        step_logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        prev = states["log_probs"]._data[:, :, None]
        fin = states["finished"]._data
        # finished beams: only end_token continues (score unchanged)
        freeze = jnp.full((vocab,), -1e9, jnp.float32).at[self.end_token].set(0.0)
        step_logp = jnp.where(fin[:, :, None], freeze[None, None, :], step_logp)
        total = (prev + step_logp).reshape(batch, self.beam_size * vocab)
        top_logp, top_idx = jax.lax.top_k(total, self.beam_size)
        beam_idx = (top_idx // vocab).astype(jnp.int64)   # [batch, beam]
        token_idx = (top_idx % vocab).astype(jnp.int64)
        new_fin = jnp.take_along_axis(fin, beam_idx, axis=1) \
            | (token_idx == self.end_token)
        lengths = jnp.take_along_axis(states["lengths"]._data, beam_idx, axis=1)
        lengths = lengths + (~new_fin).astype(jnp.int64)
        flat_parent = (jnp.arange(batch)[:, None] * self.beam_size
                       + beam_idx).reshape(-1)
        next_states = {
            "log_probs": Tensor(top_logp),
            "finished": Tensor(new_fin),
            "lengths": Tensor(lengths),
            "cell_states": [
                gather(s, Tensor(flat_parent), axis=0)
                for s in _as_list(next_cell_states)],
            "parent_idx": Tensor(beam_idx),
        }
        outputs = {"token": Tensor(token_idx), "parent": Tensor(beam_idx),
                   "log_probs": Tensor(top_logp)}
        next_inputs = Tensor(token_idx.reshape(-1))
        return outputs, next_states, next_inputs, Tensor(new_fin)

    @property
    def tracks_own_finished(self):
        return True

    def finalize(self, outputs, final_states, sequence_lengths):
        """Backtrack the beam parents into explicit token sequences
        [batch, beam, time]."""
        tokens = np.stack([np.asarray(o["token"].numpy()) for o in outputs], 0)
        parents = np.stack([np.asarray(o["parent"].numpy()) for o in outputs], 0)
        T, batch, beam = tokens.shape
        seqs = np.zeros((T, batch, beam), np.int64)
        cur = np.tile(np.arange(beam), (batch, 1))
        for t in range(T - 1, -1, -1):
            seqs[t] = np.take_along_axis(tokens[t], cur, axis=1)
            cur = np.take_along_axis(parents[t], cur, axis=1)
        out = Tensor(jnp.asarray(seqs.transpose(1, 2, 0)))  # [batch, beam, T]
        return out, final_states


def _as_list(states):
    if isinstance(states, (list, tuple)):
        return list(states)
    return [states]


def dynamic_decode(decoder, inits=None, max_step_num=None,
                   output_time_major=False, impute_finished=False,
                   is_test=False, return_length=False, **kwargs):
    """Run ``decoder`` until every sequence finishes or ``max_step_num``
    (reference decode.py dynamic_decode). Returns (outputs, final_states)
    (+ lengths when return_length)."""
    inputs, states, finished = decoder.initialize(inits)
    outputs = []
    step = 0
    # max_step_num=None means "until every sequence finishes" (reference
    # semantics) — NOT an implicit cap. A host-loop failsafe still bounds a
    # decoder that never emits end tokens, but hitting it is loud.
    limit = max_step_num if max_step_num is not None else 100_000
    while step < limit:
        out, states, inputs, finished = decoder.step(step, inputs, states,
                                                     **kwargs)
        outputs.append(out)
        step += 1
        if bool(np.asarray(finished.numpy()).all()):
            break
    else:
        if max_step_num is None:
            raise RuntimeError(
                f"dynamic_decode: {limit} steps without all sequences "
                "finishing and no max_step_num given — the decoder never "
                "emits its end token; pass max_step_num to bound decoding")
    lengths = states.get("lengths") if isinstance(states, dict) else None
    final, states = decoder.finalize(outputs, states, lengths)
    if output_time_major and isinstance(final, Tensor) and final._data.ndim >= 3:
        final = Tensor(jnp.moveaxis(final._data, -1, 0))
    if return_length:
        return final, states, lengths
    return final, states
