"""GPT model family — the flagship benchmark model.

Architecture parity: the reference's fleet GPT test models
(test/collective/fleet/hybrid_parallel_pp_transformer.py,
hybrid_parallel_mp_model.py) and the GPT-3 paper sizes named in BASELINE.md.
Pre-LN decoder blocks, learned positional embeddings, GELU MLP (4x), causal
self-attention through ``F.scaled_dot_product_attention`` (flash-attention
Pallas kernel on TPU when available).

Tensor parallelism: with ``mp_degree > 1`` (or fleet initialised), qkv/out and
mlp projections become Column/RowParallelLinear and the token embedding
VocabParallelEmbedding — the Megatron layout (reference: fleet/layers/mpu/
mp_layers.py:47,:333,:540) where GSPMD emits the collectives.
"""
from __future__ import annotations

import math
from dataclasses import dataclass

from ..framework.param_attr import ParamAttr
from ..nn import Layer, functional as F
from ..nn.initializer import Normal
from ..nn.layer.common import Dropout, Embedding, Linear
from ..nn.layer.container import LayerList
from ..nn.layer.norm import LayerNorm
from ..tensor.creation import arange
from ..tensor.manipulation import concat, reshape
from ..tensor.math import matmul


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    max_seq_len: int = 1024
    intermediate_size: int | None = None  # default 4*hidden
    hidden_dropout: float = 0.0
    attn_dropout: float = 0.0
    layer_norm_eps: float = 1e-5
    initializer_range: float = 0.02
    tie_word_embeddings: bool = True
    use_flash_attention: bool = True
    # run the Pallas kernel in interpret mode off-TPU too (CPU-mesh tests of
    # the sharded kernel path; never set in production configs)
    force_flash: bool = False
    # fused MLP-block Pallas kernels (ops/pallas/fused_mlp): single-pass
    # LN (+ residual-in/out) and bias+gelu epilogues replace the XLA
    # elementwise chains in the decoder block — the round-5 roofline's
    # ~20 ms/step of LN/gelu/residual HBM round-trips. bench.py flips this
    # via --fused-mlp; off by default until the on-chip A/B confirms it.
    fused_mlp: bool = False
    # run the fused MLP kernels in interpret mode off-TPU too (CPU tests)
    force_fused_mlp: bool = False
    # parallel knobs
    tensor_parallel: bool = False  # force TP layers even without fleet
    recompute: bool = False  # rematerialize blocks in backward (activation
    # memory ~O(layers*s*h) instead of O(layers*s*4h stacks))
    remat_save_attn: bool = True  # under recompute, also save the flash
    # kernel's o/lse (backward skips the attention re-forward for
    # ~layers*s*h*2B extra residency); memory-edge configs (1.3B on 16 GB)
    # set False to keep the smaller footprint
    remat_save_ln: bool = False  # under recompute, also save both LN
    # outputs per layer (2*layers*s*h*2B extra residency, ~1.2 GB at 760M
    # bs8): backward skips the LN re-forward (mean/var/normalize passes)
    # perf-attribution ablations (perf_breakdown.py only — differential
    # timing of step phases; never set in training configs): any of
    # {"attn", "mlp", "ce"} ("ce" keeps the lm-head matmul, drops the
    # softmax-CE math)
    ablate: tuple = ()
    # round-10 quantized serving: "int8"/"int4" quantizes the decoder
    # matmul weight stacks at serving-params extraction (fused weight-only
    # Pallas GEMM keeps them quantized in HBM); None serves fp. Group size
    # -1 = per-output-channel scales, > 0 = per-group along the in-dim.
    weight_dtype: str | None = None
    weight_quant_group_size: int = -1
    # "int8" stores the paged KV cache int8 with per-(page-slot, head)
    # scales: quantize-on-write in the unified step, dequant fused in the
    # ragged attention kernel. None keeps the compute-dtype pools.
    kv_cache_dtype: str | None = None
    # round-12 speculative decoding: > 0 verifies up to this many n-gram
    # draft tokens per decode lane per unified step (1 + k query rows
    # through the ragged attention, fused in-jit accept epilogue emitting
    # the accepted prefix + one bonus token). 0 = plain decode. The value
    # is BUILD geometry (the step's output is [batch, k + 1]); per-request
    # adaptive k varies only the spec_len inputs, never the shape.
    spec_decode_k: int = 0
    # round-19 model-based speculative drafting: > 0 selects the truncated-
    # layer SELF-DRAFT proposer for serving (the first spec_draft_layers
    # layers of the SAME serving stack — shared embeddings/positional
    # table/final LN/LM head, zero extra weights to load — run as their own
    # small fixed-shape unified-step jit over a dedicated draft KV pool,
    # proposing spec_decode_k tokens autoregressively per decode lane).
    # 0 keeps the round-12 n-gram proposer. Must be < num_layers (a full-
    # depth "draft" would just run the target twice — rejected loudly).
    spec_draft_layers: int = 0
    # round-16 megakernel decode: route ALL-DECODE serving rounds through
    # the fused per-layer Pallas megakernels (ops/pallas/mega_decode —
    # LN1 -> QKV -> inline KV quantize -> ragged paged attention -> output
    # GEMM -> residual+LN2 in ONE kernel, then the fused MLP kernel) with
    # intermediate activations pinned in VMEM instead of the per-op chain
    # XLA stitches through HBM. Mixed prefill+decode rounds keep the
    # per-op unified step; greedy mega output matches the full-forward
    # oracle token-for-token and mega=False is bit-identical to round 15.
    # Serves mesh size 1/None, fp or int8 weights (int4 rejected loudly),
    # fp or int8 KV.
    mega_decode: bool = False
    # round-25 Mixture-of-Experts: moe_experts > 0 replaces every block's
    # dense MLP with a top-k routed expert FFN (models/moe.py — capacity
    # clamping drops overflow token-choices onto the residual, ragged
    # grouped Pallas GEMM streams only the routed experts' tiles).
    # Serving runs through the per-op unified step (mega stays dense-only
    # and rejects MoE loudly); training shards the expert stacks over the
    # optional "ep" mesh axis (gpt_spmd + distributed/mesh.py).
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01  # load-balance loss weight (training)

    @property
    def ffn_size(self) -> int:
        return self.intermediate_size or 4 * self.hidden_size

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    def num_params(self) -> int:
        h, v, l = self.hidden_size, self.vocab_size, self.num_layers
        f, e = self.ffn_size, self.moe_experts
        if e:
            # router gate + E stacked expert FFNs replace the dense MLP
            mlp = h * e + e * (2 * h * f + h + f)
        else:
            mlp = 2 * h * f + h + f
        per_layer = 4 * h * h + 4 * h + mlp + 4 * h
        emb = v * h + self.max_seq_len * h
        return emb + l * per_layer + 2 * h


# GPT-3 paper table 2.1 sizes (the BASELINE.md benchmark ladder).
GPT_CONFIGS: dict[str, GPTConfig] = {
    "gpt3-tiny": GPTConfig(vocab_size=1024, hidden_size=128, num_layers=2, num_heads=4, max_seq_len=128),
    "gpt3-125m": GPTConfig(hidden_size=768, num_layers=12, num_heads=12),
    "gpt3-350m": GPTConfig(hidden_size=1024, num_layers=24, num_heads=16),
    "gpt3-760m": GPTConfig(hidden_size=1536, num_layers=24, num_heads=16),
    "gpt3-1.3b": GPTConfig(hidden_size=2048, num_layers=24, num_heads=32, max_seq_len=2048),
    "gpt3-2.7b": GPTConfig(hidden_size=2560, num_layers=32, num_heads=32, max_seq_len=2048),
    "gpt3-6.7b": GPTConfig(hidden_size=4096, num_layers=32, num_heads=32, max_seq_len=2048),
    "gpt3-13b": GPTConfig(hidden_size=5120, num_layers=40, num_heads=40, max_seq_len=2048),
}


def _w(config: GPTConfig) -> ParamAttr:
    """GPT init: N(0, initializer_range) on all weight matrices (the paper's
    scheme; the reference test models use Normal(std=0.02) likewise)."""
    return ParamAttr(initializer=Normal(mean=0.0, std=config.initializer_range))


from ._tp import tp_enabled as _tp_enabled  # noqa: E402 (shared TP wiring)


def _linear(config, in_f, out_f, kind):
    """kind: 'col' | 'row' | 'plain' — GPT linears keep their biases."""
    from ._tp import tp_linear

    return tp_linear(config, in_f, out_f, kind, _w(config), has_bias=True)


class GPTEmbeddings(Layer):
    """Token + learned position embeddings with dropout."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        if _tp_enabled(config):
            from ..distributed.fleet.meta_parallel.mp_layers import VocabParallelEmbedding

            self.word_embeddings = VocabParallelEmbedding(
                config.vocab_size, config.hidden_size, weight_attr=_w(config)
            )
        else:
            self.word_embeddings = Embedding(
                config.vocab_size, config.hidden_size, weight_attr=_w(config)
            )
        self.position_embeddings = Embedding(
            config.max_seq_len, config.hidden_size, weight_attr=_w(config)
        )
        self.dropout = Dropout(config.hidden_dropout)

    def forward(self, input_ids, position_ids=None, past_len: int = 0):
        if position_ids is None:
            seq_len = input_ids.shape[-1]
            position_ids = arange(past_len, past_len + seq_len, dtype="int64")
        return self.dropout(
            self.word_embeddings(input_ids)
            + self.position_embeddings(position_ids)
        )


class GPTAttention(Layer):
    """Causal multi-head self-attention (fused qkv projection)."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        h = config.hidden_size
        self.qkv_proj = _linear(config, h, 3 * h, "col")
        self.out_proj = _linear(config, h, h, "row")
        self.attn_dropout = config.attn_dropout
        self.resid_dropout = Dropout(config.hidden_dropout)

    def forward(self, x, attn_mask=None, cache=None):
        cfg = self.config
        b, s = x.shape[0], x.shape[1]
        qkv = self.qkv_proj(x)  # [b, s, 3h]
        qkv = reshape(qkv, [b, s, 3, cfg.num_heads, cfg.head_dim])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]  # [b, s, nh, hd]
        new_cache = None
        past_len = 0
        if cache is not None:
            k_past, v_past = cache
            if k_past is not None:
                past_len = k_past.shape[1]
                k = concat([k_past, k], axis=1)
                v = concat([v_past, v], axis=1)
            new_cache = (k, v)
        # causal handles the cached-prefix case too: _sdpa_ref offsets the
        # tril by (k_len - q_len), i.e. query t attends keys <= past_len + t.
        causal = attn_mask is None and s > 1
        out = F.scaled_dot_product_attention(
            q, k, v,
            attn_mask=attn_mask,
            is_causal=causal,
            dropout_p=self.attn_dropout if self.training else 0.0,
        )  # [b, s, nh, hd]
        out = reshape(out, [b, s, cfg.num_heads * cfg.head_dim])
        out = self.resid_dropout(self.out_proj(out))
        if cache is not None:
            return out, new_cache
        return out


class GPTMLP(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        h, f = config.hidden_size, config.ffn_size
        self.fc1 = _linear(config, h, f, "col")
        self.fc2 = _linear(config, f, h, "row")
        self.dropout = Dropout(config.hidden_dropout)

    def forward(self, x):
        if _fused_mlp_on(self.config):
            from ..incubate.nn import functional as FI

            # bias+gelu ride ONE Pallas epilogue kernel after the GEMM
            y = FI.fused_bias_gelu(
                matmul(x, self.fc1.weight), self.fc1.bias,
                use_pallas=True if self.config.force_fused_mlp else None)
            return self.dropout(self.fc2(y))
        return self.dropout(self.fc2(F.gelu(self.fc1(x), approximate=True)))


def _fused_mlp_on(config: GPTConfig) -> bool:
    # under TP the block runs global-view with mp-sharded weights; GSPMD
    # cannot partition a pallas_call, so the fused path is single-shard only
    return getattr(config, "fused_mlp", False) and not _tp_enabled(config)


class GPTDecoderLayer(Layer):
    """Pre-LN transformer decoder block."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.ln_1 = LayerNorm(config.hidden_size, epsilon=config.layer_norm_eps)
        self.attn = GPTAttention(config)
        self.ln_2 = LayerNorm(config.hidden_size, epsilon=config.layer_norm_eps)
        if getattr(config, "moe_experts", 0):
            from .moe import GPTMoE

            self.mlp = GPTMoE(config)
        else:
            self.mlp = GPTMLP(config)

    def forward(self, x, attn_mask=None, cache=None):
        if _fused_mlp_on(self.config):
            return self._forward_fused(x, attn_mask=attn_mask, cache=cache)
        if cache is not None:
            a, new_cache = self.attn(self.ln_1(x), attn_mask=attn_mask, cache=cache)
            x = x + a
            x = x + self.mlp(self.ln_2(x))
            return x, new_cache
        x = x + self.attn(self.ln_1(x), attn_mask=attn_mask)
        x = x + self.mlp(self.ln_2(x))
        return x

    def _forward_fused(self, x, attn_mask=None, cache=None):
        """Fused-kernel block: LN1 single-pass, then the attention branch's
        residual add + LN2 in ONE residual-in/residual-out kernel."""
        from ..incubate.nn import functional as FI

        cfg = self.config
        uk = True if cfg.force_fused_mlp else None
        y1 = FI.fused_layer_norm(x, self.ln_1.weight, self.ln_1.bias,
                                 epsilon=cfg.layer_norm_eps, use_pallas=uk)
        new_cache = None
        if cache is not None:
            a, new_cache = self.attn(y1, attn_mask=attn_mask, cache=cache)
        else:
            a = self.attn(y1, attn_mask=attn_mask)
        # s = x + a (residual-out) and y2 = LN(s), one kernel
        y2, s = FI.fused_ln_residual(a, x, self.ln_2.weight, self.ln_2.bias,
                                     epsilon=cfg.layer_norm_eps, use_pallas=uk)
        x = s + self.mlp(y2)
        if cache is not None:
            return x, new_cache
        return x


class GPTModel(Layer):
    """Embeddings + decoder stack + final LN."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.embeddings = GPTEmbeddings(config)
        self.layers = LayerList([GPTDecoderLayer(config) for _ in range(config.num_layers)])
        self.ln_f = LayerNorm(config.hidden_size, epsilon=config.layer_norm_eps)

    def forward(self, input_ids, position_ids=None, attn_mask=None, caches=None):
        past_len = 0
        if caches is not None and caches[0][0] is not None:
            past_len = caches[0][0].shape[1]
        x = self.embeddings(input_ids, position_ids, past_len=past_len)
        new_caches = [] if caches is not None else None
        use_recompute = (getattr(self.config, "recompute", False)
                         and self.training and caches is None)
        if use_recompute:
            from ..distributed.fleet.utils import recompute

        for i, layer in enumerate(self.layers):
            if caches is not None:
                x, c = layer(x, attn_mask=attn_mask, cache=caches[i])
                new_caches.append(c)
            elif use_recompute:
                x = recompute(layer, x, attn_mask=attn_mask)
            else:
                x = layer(x, attn_mask=attn_mask)
        x = self.ln_f(x)
        if caches is not None:
            return x, new_caches
        return x

    def generate(self, input_ids, max_new_tokens=20, **kw):
        """Greedy decoding over the paged KV cache with the tied-embedding
        LM head — see :func:`generate_paged`."""
        return generate_paged(self, input_ids, max_new_tokens, **kw)


class GPTPretrainingCriterion(Layer):
    """Shifted next-token cross-entropy (mean over tokens)."""

    def forward(self, logits, labels):
        # logits [b, s, v], labels [b, s]
        loss = F.cross_entropy(
            reshape(logits, [-1, logits.shape[-1]]),
            reshape(labels, [-1]),
            reduction="mean",
        )
        return loss


class GPTForCausalLM(Layer):
    """GPTModel + LM head (weight-tied by default) + optional loss."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.gpt = GPTModel(config)
        if not config.tie_word_embeddings:
            self.lm_head = Linear(
                config.hidden_size, config.vocab_size,
                weight_attr=_w(config), bias_attr=False,
            )
        self.criterion = GPTPretrainingCriterion()

    def _logits(self, hidden):
        if self.config.tie_word_embeddings:
            w = self.gpt.embeddings.word_embeddings.weight  # [v, h]
            return matmul(hidden, w, transpose_y=True)
        return self.lm_head(hidden)

    def forward(self, input_ids, labels=None, position_ids=None, attn_mask=None, caches=None):
        if caches is not None:
            hidden, new_caches = self.gpt(
                input_ids, position_ids=position_ids, attn_mask=attn_mask, caches=caches
            )
            return self._logits(hidden), new_caches
        hidden = self.gpt(input_ids, position_ids=position_ids, attn_mask=attn_mask)
        logits = self._logits(hidden)
        if labels is None:
            return logits
        # standard LM shift: predict token t+1 from prefix ..t
        shift_logits = logits[:, :-1, :]
        shift_labels = labels[:, 1:]
        return self.criterion(shift_logits, shift_labels)

    def generate(self, input_ids, max_new_tokens=20, **kw):
        """Greedy autoregressive decoding over the paged KV cache — see
        :func:`generate_paged`."""
        return generate_paged(self, input_ids, max_new_tokens, **kw)


# ---------------------------------------------------------------------------
# Round-7 serving path: paged KV cache + fixed-shape decode step.
#
# The autoregressive analog of gpt_spmd's training step: pure functions over
# a params pytree EXTRACTED from the Layer model (one-time, zero-copy on the
# underlying arrays), so prefill compiles as ONE jit and every decode step
# replays ONE fixed-shape jit — no per-token Python dispatch, no retrace
# (MPK's whole-step-as-one-program argument, arxiv 2512.22219). K/V live in
# the paged pool managed by inference.kv_cache.KVCacheManager and attention
# over the ragged batch runs the Pallas paged decode kernel
# (ops/pallas/paged_attention, arxiv 2604.15464).
# ---------------------------------------------------------------------------


# the ONE per-layer weight table: serving_params' stacks AND the params
# cache's staleness walk both derive from it, so adding a per-layer weight
# cannot desync the cache oracle from the extraction
_SRV_LAYER_WEIGHTS = (
    ("ln1_g", lambda l: l.ln_1.weight), ("ln1_b", lambda l: l.ln_1.bias),
    ("wqkv", lambda l: l.attn.qkv_proj.weight),
    ("bqkv", lambda l: l.attn.qkv_proj.bias),
    ("wo", lambda l: l.attn.out_proj.weight),
    ("bo", lambda l: l.attn.out_proj.bias),
    ("ln2_g", lambda l: l.ln_2.weight), ("ln2_b", lambda l: l.ln_2.bias),
    ("w1", lambda l: l.mlp.fc1.weight), ("b1", lambda l: l.mlp.fc1.bias),
    ("w2", lambda l: l.mlp.fc2.weight), ("b2", lambda l: l.mlp.fc2.bias),
)

# MoE blocks swap the dense-MLP rows for the stacked expert tree (the
# [E, ...] stacks gain the usual leading [L] dim at extraction)
_SRV_MOE_WEIGHTS = (
    ("moe_gate", lambda l: l.mlp.gate_weight),
    ("moe_w1", lambda l: l.mlp.w1), ("moe_b1", lambda l: l.mlp.b1),
    ("moe_w2", lambda l: l.mlp.w2), ("moe_b2", lambda l: l.mlp.b2),
)
_DENSE_MLP_KEYS = ("w1", "b1", "w2", "b2")


def _srv_layer_weight_table(config):
    if getattr(config, "moe_experts", 0):
        return tuple(kv for kv in _SRV_LAYER_WEIGHTS
                     if kv[0] not in _DENSE_MLP_KEYS) + _SRV_MOE_WEIGHTS
    return _SRV_LAYER_WEIGHTS


def _srv_nonlayer_weights(model):
    gpt = model.gpt if hasattr(model, "gpt") else model
    ws = [("tok_emb", gpt.embeddings.word_embeddings.weight),
          ("pos_emb", gpt.embeddings.position_embeddings.weight),
          ("lnf_g", gpt.ln_f.weight), ("lnf_b", gpt.ln_f.bias)]
    if getattr(model, "lm_head", None) is not None:
        ws.append(("lm_head", model.lm_head.weight))
    return ws


def _serving_weight_buffers(model):
    """The model's live weight buffers — buffer identity is the staleness
    key for the per-model params cache (an optimizer step rebinds
    ``._data``, so stale ids mean re-extract)."""
    gpt = model.gpt if hasattr(model, "gpt") else model
    bufs = [t._data for _, t in _srv_nonlayer_weights(model)]
    table = _srv_layer_weight_table(gpt.config)
    for l in gpt.layers:
        bufs += [get(l)._data for _, get in table]
    return bufs


def serving_params(model):
    """Extract the serving params pytree from a GPTForCausalLM / GPTModel.

    Per-layer weights stack on a leading [L, ...] dim so the blocks run
    under ``lax.scan`` (one compiled block, not L unrolled copies). The
    stacks are device COPIES (~1x extra weight memory while they live);
    the embeddings / final-LN / lm-head leaves are views of the live
    buffers. ``generate_paged`` caches the extraction per model (see
    :func:`_serving_params_cached`) so repeated calls don't re-stack.
    """
    import jax.numpy as jnp

    gpt = model.gpt if hasattr(model, "gpt") else model
    cfg = gpt.config
    if _tp_enabled(cfg):
        raise NotImplementedError(
            "serving params extract from a single-shard eager model; for "
            "multi-chip serving pass mesh=... to generate_paged / "
            "ServingPredictor (round-11 SPMD serving) instead of enabling "
            "the eager TP layers")

    params = {k: t._data for k, t in _srv_nonlayer_weights(model)}
    params["layers"] = {
        k: jnp.stack([get(l)._data for l in gpt.layers])
        for k, get in _srv_layer_weight_table(cfg)
    }
    return params  # lm_head (when untied) rides _srv_nonlayer_weights


# NOTE: _srv_ln/_srv_mlp/the prefill block are the serving-side pure
# spellings of the decoder block — keep their math in lockstep with the
# eager Layer classes above AND gpt_spmd's _layer_norm/_block_mlp (same
# params-dict key schema); a drift in eps/gelu/LN-stat handling makes
# generate() disagree with the trained model. The fp32 LN statistics here
# are intentional (decode runs the weights' dtype, stats stay fp32).
def _srv_ln(x, g, b, eps):
    import jax

    import jax.numpy as jnp

    xf = x.astype(jnp.float32)
    mu = xf.mean(-1, keepdims=True)
    var = xf.var(-1, keepdims=True)
    return (((xf - mu) * jax.lax.rsqrt(var + eps)) * g + b).astype(x.dtype)


def _srv_logits(params, h):
    """h [..., hidden] -> logits [..., vocab] (tied head unless lm_head)."""
    import jax.numpy as jnp

    if "lm_head" in params:
        return h @ params["lm_head"]
    return jnp.einsum("...h,vh->...v", h, params["tok_emb"])


def _srv_mm(y, w, use_kernel=None):
    """The serving matmul: fp weights ride the plain dot; quantized stacks
    (``{"q": int8|packed-int4, "s": scales}`` — see inference/quantize.py)
    ride the fused weight-only Pallas GEMM, staying quantized in HBM.
    ``use_kernel`` follows the paged-attention contract (None = kernel on
    TPU / jnp oracle elsewhere; True forces interpret mode — CPU tests;
    False forces the dequant-matmul reference)."""
    if isinstance(w, dict):
        from ..ops.pallas.quant_matmul import quant_matmul

        return quant_matmul(y, w["q"], w["s"], use_kernel=use_kernel)
    return y @ w


def _srv_psum(x, axis):
    """The serving collective hook: under the mp mesh the row-parallel
    matmul partials all-reduce here; single-chip (axis None) it is the
    identity — ONE spelling of the block math serves both paths."""
    import jax

    return jax.lax.psum(x, axis) if axis else x


def _srv_mlp(p, y, use_kernel=None, axis=None):
    import jax

    return (_srv_psum(
        _srv_mm(jax.nn.gelu(_srv_mm(y, p["w1"], use_kernel) + p["b1"],
                            approximate=True), p["w2"], use_kernel), axis)
            + p["b2"])


def _srv_moe(config, p, y, use_kernel=None, valid=None):
    """The serving MoE FFN: the SAME :func:`models.moe.moe_ffn` the eager
    oracle runs, over the packed token rows. ``valid`` (tok_slot >= 0 in
    the unified step) keeps padding rows out of the capacity race — they
    route nowhere and output zero. Expert stacks are replicated under the
    mp mesh (``serving_param_specs`` P() fallback), so there is no psum:
    each chip computes the full MoE output redundantly — acceptable for
    the per-op path this round (experts are small relative to KV)."""
    lead = y.shape[:-1]
    tokens = y.reshape(-1, y.shape[-1])
    v = None if valid is None else valid.reshape(-1)
    from .moe import moe_ffn

    out, _aux = moe_ffn(
        tokens, p["moe_gate"], p["moe_w1"], p["moe_b1"], p["moe_w2"],
        p["moe_b2"], top_k=config.moe_top_k,
        capacity_factor=config.moe_capacity_factor,
        use_kernel=use_kernel, valid=v)
    return out.reshape(*lead, out.shape[-1])


def _srv_ffn(config, p, y, use_kernel=None, axis=None, valid=None):
    """Block FFN dispatch: dense ``_srv_mlp`` vs routed ``_srv_moe`` —
    the ONE switch every serving builder goes through."""
    if getattr(config, "moe_experts", 0):
        return _srv_moe(config, p, y, use_kernel, valid=valid)
    return _srv_mlp(p, y, use_kernel, axis)


def _split_qkv(qkv, nh, hd, head_major):
    """[..., 3*nh*hd] -> (q, k, v) each [..., nh, hd]. The eager layout
    orders the fused projection's columns [3, nh, hd]; the mesh layout is
    HEAD-MAJOR [nh, 3, hd] (``shard_serving_params`` permutes the columns)
    so a contiguous mp shard owns whole heads. Both splits read the same
    dot products — bit-identical outputs, only column order moves."""
    lead = qkv.shape[:-1]
    if head_major:
        q4 = qkv.reshape(*lead, nh, 3, hd)
        return q4[..., 0, :], q4[..., 1, :], q4[..., 2, :]
    q4 = qkv.reshape(*lead, 3, nh, hd)
    return q4[..., 0, :, :], q4[..., 1, :, :], q4[..., 2, :, :]


# ---------------------------------------------------------------------------
# Round-11 multi-chip SPMD serving: Megatron tensor-parallel layout for the
# serving pytree over a Mesh(("mp",)). Column-parallel stacks (wqkv, w1 —
# qkv permuted head-major first) shard their output dim, row-parallel
# stacks (wo, w2) their input dim; embeddings / LM head / LN / row biases
# stay replicated. The KV page pools and their int8 scale planes shard on
# the HEAD axis (each chip owns its heads' pages end to end — zero KV
# bytes on the wire); the only collectives in a serving step are the two
# row-parallel psums per layer (_srv_psum).
# ---------------------------------------------------------------------------


def _head_major_perm(nh, hd):
    """Column permutation taking the fused qkv projection's [3, nh, hd]
    output order to [nh, 3, hd] — whole heads become contiguous so the mp
    axis shards them (a contiguous chunk of the eager layout would split
    the q/k/v thirds, not the heads)."""
    import numpy as np

    return np.arange(3 * nh * hd).reshape(3, nh, hd).transpose(
        1, 0, 2).reshape(-1)


def serving_param_specs(params, axis="mp"):
    """PartitionSpec tree mirroring a serving params pytree (fp or
    quantized) — the serving twin of ``gpt_spmd.param_specs``. Quantized
    ``{"q", "s"}`` stacks shard with their weight: column scales follow
    the output dim; row (K-sharded) group scales shard over the group dim,
    per-channel row scales replicate (each chip's partial product scales
    by the same output-channel factor before the psum)."""
    from jax.sharding import PartitionSpec as P

    col = {"wqkv", "w1"}
    row = {"wo", "w2"}
    cbias = {"bqkv", "b1"}

    def stack_spec(key, leaf):
        if key in col:
            if isinstance(leaf, dict):
                return {"q": P(None, None, axis), "s": P(None, None, axis)}
            return P(None, None, axis)
        if key in row:
            if isinstance(leaf, dict):
                s_spec = (P(None, axis, None) if leaf["s"].shape[1] > 1
                          else P())
                return {"q": P(None, axis, None), "s": s_spec}
            return P(None, axis, None)
        if key in cbias:
            return P(None, axis)
        return P()

    out = {k: P() for k in params if k != "layers"}
    out["layers"] = {k: stack_spec(k, v)
                     for k, v in params["layers"].items()}
    return out


def shard_serving_params(params, mesh, config):
    """Lay a serving params pytree (fp or quantized) out over the mp mesh:
    permute wqkv/bqkv head-major, validate divisibility, and device_put
    every leaf under :func:`serving_param_specs`. Returns a NEW pytree of
    committed sharded arrays (the unsharded source stays usable)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..inference.quantize import assert_quant_shardable

    mp = int(mesh.shape["mp"])
    nh, hd = config.num_heads, config.head_dim
    if nh % mp:
        raise ValueError(
            f"the mp mesh size {mp} must divide num_heads {nh} "
            "(heads shard whole)")
    if config.ffn_size % mp:
        raise ValueError(
            f"the mp mesh size {mp} must divide ffn_size {config.ffn_size}")
    assert_quant_shardable(params["layers"], mp,
                           getattr(config, "weight_dtype", None))
    perm = jnp.asarray(_head_major_perm(nh, hd))

    def permute(leaf):
        if isinstance(leaf, dict):
            return {"q": leaf["q"][..., perm], "s": leaf["s"][..., perm]}
        return leaf[..., perm]

    layers = dict(params["layers"])
    layers["wqkv"] = permute(layers["wqkv"])
    layers["bqkv"] = layers["bqkv"][..., perm]
    out = dict(params)
    out["layers"] = layers
    specs = serving_param_specs(out)
    shardings = jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                             is_leaf=lambda x: isinstance(x, P))
    return jax.device_put(out, shardings)


def _mesh_mp(mesh):
    """(mp degree, psum axis name or None) for a serving mesh argument."""
    if mesh is None:
        return 1, None
    return int(mesh.shape["mp"]), "mp"


# KV pool / scale-plane PartitionSpecs under the serving mesh: pools are
# [L, num_pages, page_size, kv_heads, head_dim] (scales drop the trailing
# head_dim) — the HEAD axis shards, so every chip owns its heads' pages
# (and their scales) end to end: quantize-on-write, CoW copies and prefix
# reuse all stay chip-local, zero KV bytes cross the interconnect.
def _kv_specs():
    from jax.sharding import PartitionSpec as P

    return P(None, None, None, "mp", None), P(None, None, None, "mp")


def build_prefill(config: GPTConfig, page_size: int,
                  use_kernel: bool | None = None, mesh=None):
    """One-jit prefill: forward the (right-padded) prompts, scatter each
    slot's K/V into its pages, return the next-token ids + logits at each
    prompt's last valid position.

    Signature: ``fn(params, ids[b,s], lengths[b], k_pages, v_pages,
    pages[b,pps]) -> (next_ids[b], logits[b,v], k_pages, v_pages)``.
    Ragged prompts ride right-padding: causal masking keeps padded columns
    out of every valid row's softmax, and the page scatter drops positions
    past each length.

    ``mesh`` (round 11): a ``Mesh(("mp",))`` shards the step — params per
    :func:`serving_param_specs` (head-major qkv), pools on the head axis —
    via ``shard_map``; attention/K-V writes run chip-local over each
    chip's heads and only the row-parallel matmuls psum. The signature,
    donation and trace-count contract are unchanged.
    """
    import jax
    import jax.numpy as jnp

    from ..inference.kv_cache import paged_write_prefill

    cfg = config
    if getattr(cfg, "moe_experts", 0):
        raise ValueError(
            "build_prefill predates the packed unified step and has no "
            "MoE FFN path — serve moe_experts > 0 through "
            "build_unified_step / ServingPredictor")
    eps = cfg.layer_norm_eps
    trace_count = [0]
    mp, axis = _mesh_mp(mesh)
    nh_l, hd = cfg.num_heads // mp, cfg.head_dim

    def _prefill_inner(params, ids, lengths, k_pages, v_pages, pages):
        b, s = ids.shape
        x = (jnp.take(params["tok_emb"], ids, axis=0)
             + params["pos_emb"][:s])

        def block(x, p):
            y = _srv_ln(x, p["ln1_g"], p["ln1_b"], eps)
            qkv = _srv_mm(y, p["wqkv"], use_kernel) + p["bqkv"]
            q, k, v = _split_qkv(qkv, nh_l, hd, head_major=mesh is not None)
            s_ = jnp.einsum("bqnd,bknd->bnqk", q.astype(jnp.float32),
                            k.astype(jnp.float32)) / math.sqrt(hd)
            causal = jnp.tril(jnp.ones((s, s), bool))
            s_ = jnp.where(causal[None, None], s_, -1e30)
            a = jnp.einsum("bnqk,bknd->bqnd",
                           jax.nn.softmax(s_, axis=-1),
                           v.astype(jnp.float32)).astype(x.dtype)
            x = x + _srv_psum(_srv_mm(a.reshape(b, s, nh_l * hd), p["wo"],
                                      use_kernel), axis) + p["bo"]
            x = x + _srv_mlp(p, _srv_ln(x, p["ln2_g"], p["ln2_b"], eps),
                             use_kernel, axis)
            return x, (k, v)

        x, (ks, vs) = jax.lax.scan(block, x, params["layers"])
        x = _srv_ln(x, params["lnf_g"], params["lnf_b"], eps)
        h_last = x[jnp.arange(b), jnp.maximum(lengths - 1, 0)]
        logits = _srv_logits(params, h_last).astype(jnp.float32)
        next_ids = jnp.argmax(logits, axis=-1).astype(jnp.int32)

        # copy-on-prefill: scatter every slot's K/V into its pages.
        # ks: [L, b, s, nh, hd] -> per (layer, slot) writes, vmapped over L
        def write_all(pool, seqs):
            for bi in range(b):  # b is static; unrolls into b scatters
                pool = jax.vmap(
                    paged_write_prefill, in_axes=(0, 0, None, None, None)
                )(pool, seqs[:, bi], pages[bi], lengths[bi], page_size)
            return pool

        k_pages = write_all(k_pages, ks)
        v_pages = write_all(v_pages, vs)
        return next_ids, logits, k_pages, v_pages

    def prefill(params, ids, lengths, k_pages, v_pages, pages):
        trace_count[0] += 1
        if mesh is not None:
            from jax.sharding import PartitionSpec as P

            kv_spec, _ = _kv_specs()
            body = jax.shard_map(
                _prefill_inner, mesh=mesh,
                in_specs=(serving_param_specs(params), P(), P(), kv_spec,
                          kv_spec, P()),
                out_specs=(P(), P(), kv_spec, kv_spec),
                check_vma=False)
        else:
            body = _prefill_inner
        # MXU-native matmul precision (gpt_spmd.loss_fn convention): the
        # framework-global "highest" would emulate bf16 serving matmuls
        # multi-pass, 3-6x slower; attention scores stay explicit fp32
        with jax.default_matmul_precision("default"):
            return body(params, ids, lengths, k_pages, v_pages, pages)

    # donate the pools like the decode step: every admission threads the
    # full cache through this jit, and an un-donated scatter would copy it
    jitted = jax.jit(prefill, donate_argnums=(3, 4))
    # one executable per prompt-length bucket: the counter makes the
    # bucketed-prefill compile count visible (bench_serve prefill_retraces)
    jitted.trace_count = trace_count
    return jitted


def build_decode_step(config: GPTConfig, page_size: int,
                      use_kernel: bool | None = None, mesh=None):
    """The fixed-shape decode step, compiled once per (batch, cache
    geometry): embed the incoming token, write its K/V into the pages,
    paged-attend over every layer, emit the greedy next token.

    Signature: ``fn(params, ids[b], lengths[b], k_pages, v_pages,
    page_table[b,pps]) -> (next_ids[b], logits[b,v], k_pages, v_pages)``.
    ``lengths`` counts tokens already cached per slot (0 = empty slot —
    its lane computes masked garbage and writes nothing). Every array
    argument keeps its shape step over step, so after the first call the
    loop replays one compiled program — ``fn.trace_count[0]`` exposes the
    trace count for the no-retrace gate.

    ``mesh`` (round 11): shard over ``Mesh(("mp",))`` — the paged
    attention kernel runs per chip over its own heads' pages (shard_map;
    GSPMD never sees the pallas_call), psums only on the row-parallel
    matmuls. Same signature/donation/trace contract.
    """
    import jax
    import jax.numpy as jnp

    from ..inference.kv_cache import paged_write_tokens
    from ..ops.pallas.paged_attention import paged_attention

    cfg = config
    if getattr(cfg, "moe_experts", 0):
        raise ValueError(
            "build_decode_step predates the packed unified step and has "
            "no MoE FFN path — serve moe_experts > 0 through "
            "build_unified_step / ServingPredictor")
    eps = cfg.layer_norm_eps
    trace_count = [0]
    mp, axis = _mesh_mp(mesh)
    nh_l, hd = cfg.num_heads // mp, cfg.head_dim

    def _step_inner(params, ids, lengths, k_pages, v_pages, page_table):
        b = ids.shape[0]
        active = lengths > 0
        pos = jnp.where(active, lengths, -1)  # write position = current len
        pos_emb_idx = jnp.clip(jnp.maximum(lengths, 0),
                               0, params["pos_emb"].shape[0] - 1)
        x = (jnp.take(params["tok_emb"], jnp.maximum(ids, 0), axis=0)
             + params["pos_emb"][pos_emb_idx])          # [b, h]
        ctx = jnp.where(active, lengths + 1, 0).astype(jnp.int32)

        def block(x, layer):
            p, kp, vp = layer
            y = _srv_ln(x, p["ln1_g"], p["ln1_b"], eps)
            qkv = _srv_mm(y, p["wqkv"], use_kernel) + p["bqkv"]
            q, k_tok, v_tok = _split_qkv(qkv, nh_l, hd,
                                         head_major=mesh is not None)
            kp = paged_write_tokens(kp, k_tok, page_table, pos, page_size)
            vp = paged_write_tokens(vp, v_tok, page_table, pos, page_size)
            a = paged_attention(q, kp, vp, page_table, ctx,
                                use_kernel=use_kernel)  # [b, nh_l, hd]
            x = x + _srv_psum(_srv_mm(a.reshape(b, nh_l * hd), p["wo"],
                                      use_kernel), axis) + p["bo"]
            x = x + _srv_mlp(p, _srv_ln(x, p["ln2_g"], p["ln2_b"], eps),
                             use_kernel, axis)
            return x, (kp, vp)

        x, (k_pages, v_pages) = jax.lax.scan(
            block, x, (params["layers"], k_pages, v_pages))
        x = _srv_ln(x, params["lnf_g"], params["lnf_b"], eps)
        logits = _srv_logits(params, x).astype(jnp.float32)
        next_ids = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return next_ids, logits, k_pages, v_pages

    def step(params, ids, lengths, k_pages, v_pages, page_table):
        trace_count[0] += 1
        if mesh is not None:
            from jax.sharding import PartitionSpec as P

            kv_spec, _ = _kv_specs()
            body = jax.shard_map(
                _step_inner, mesh=mesh,
                in_specs=(serving_param_specs(params), P(), P(), kv_spec,
                          kv_spec, P()),
                out_specs=(P(), P(), kv_spec, kv_spec),
                check_vma=False)
        else:
            body = _step_inner
        # MXU-native matmul precision — see build_prefill
        with jax.default_matmul_precision("default"):
            return body(params, ids, lengths, k_pages, v_pages, page_table)

    # donate the page pools: the step rewrites them, and double-buffering
    # the cache (the biggest serving allocation) would halve capacity
    jitted = jax.jit(step, donate_argnums=(3, 4))
    jitted.trace_count = trace_count
    return jitted


def _sample_epilogue(logits, keys, temperature, top_k, top_p):
    """Seeded temperature / top-k / top-p sampling, fused into the unified
    step (one [batch, vocab] sort + categorical — no host round-trip).

    logits: [b, v] fp32; keys: [b, 2] uint32 per-lane PRNG keys;
    temperature/top_p: [b] f32; top_k: [b] i32 (<= 0 disables the k
    filter, top_p outside (0, 1) disables the p filter). Ties at the k-th
    /p-th value all stay in the candidate set. Returns sampled ids [b]
    int32 — the caller selects argmax instead wherever temperature == 0.
    """
    import jax
    import jax.numpy as jnp

    v = logits.shape[-1]
    t = jnp.maximum(temperature, 1e-6).astype(jnp.float32)
    scaled = (logits / t[:, None]).astype(jnp.float32)
    sorted_desc = -jnp.sort(-scaled, axis=-1)                 # [b, v]
    k = jnp.clip(jnp.where(top_k > 0, top_k, v), 1, v).astype(jnp.int32)
    kth = jnp.take_along_axis(sorted_desc, (k - 1)[:, None], axis=1)
    keep = scaled >= kth
    probs = jax.nn.softmax(sorted_desc, axis=-1)
    cum_exclusive = jnp.cumsum(probs, axis=-1) - probs
    p_active = (top_p > 0.0) & (top_p < 1.0)
    # tokens whose preceding cumulative mass is < p stay (>= 1 survivor)
    n_keep = jnp.maximum(
        jnp.sum((cum_exclusive < top_p[:, None]).astype(jnp.int32),
                axis=-1), 1)
    n_keep = jnp.where(p_active, n_keep, v).astype(jnp.int32)
    pth = jnp.take_along_axis(sorted_desc, (n_keep - 1)[:, None], axis=1)
    keep &= scaled >= pth
    masked = jnp.where(keep, scaled, jnp.float32(-1e30))
    sampled = jax.vmap(jax.random.categorical)(keys, masked)
    return sampled.astype(jnp.int32)


def build_unified_step(config: GPTConfig, page_size: int, chunk: int,
                       use_kernel: bool | None = None,
                       kv_quant: bool = False, mesh=None,
                       spec_k: int = 0, mega: bool = False):
    """ONE fixed-shape serving step for mixed ragged prefill + decode,
    driven by a per-step TOKEN BUDGET.

    The round-9 replacement for the prefill/decode jit split. The step's
    dense compute (embeddings, qkv/out/mlp matmuls, LNs, logits) runs over
    a PACKED token stream — ``tok_ids[budget]`` with per-token owning slot
    and absolute position — so a step that decodes 7 lanes and prefills a
    9-token chunk spends exactly 16 tokens of matmul, not
    ``batch * chunk``. Only the paged-attention kernel sees the per-slot
    ``[batch, chunk]`` chunk blocks (queries scatter in, outputs gather
    back); every slot contributes 0..chunk tokens per step, causal within
    its chunk, so admission never head-of-line-blocks decode behind a full
    prompt forward.

    Signature::

        fn(params, tok_ids[t], tok_slot[t], tok_pos[t],
           q_lens[b], kv_lens[b], last_idx[b],
           feedback[t], prev_toks[b], emit_mask[b], produced[b],
           k_pages, v_pages,
           page_table[b,pps], cow_src[b], cow_dst[b], base_keys[b,2],
           temperature[b], top_k[b], top_p[b])
        -> (next_toks[b], logits[b,v], k_pages, v_pages)

    ``tok_slot < 0`` marks padding tokens (their writes drop, their rows
    compute garbage nothing reads). ``kv_lens`` counts tokens already
    cached per slot BEFORE this step; ``q_lens`` the tokens it feeds now;
    ``last_idx[b]`` indexes each slot's LAST packed token (sentinel ``t``
    when idle) — the position whose logits become the slot's next-token
    decision, meaningful only when the chunk reaches the end of the
    slot's context (the scheduler knows). Copy-on-write lanes duplicate
    page ``cow_src -> cow_dst`` across every layer before any write
    (``cow_dst == num_pages`` is the no-op sentinel). Greedy lanes
    (``temperature == 0``) take the same argmax as the round-7 decode
    step, bit-identical; sampling lanes run the fused seeded epilogue.
    Every array argument keeps its shape step over step: one trace, one
    executable (``fn.trace_count[0]`` is the gate).

    DEVICE-RESIDENT FEEDBACK (round 13, the async engine's enabler):
    ``feedback[t]`` marks packed tokens whose id the HOST DOES NOT KNOW
    YET — the step reads them from ``prev_toks[tok_slot]`` instead of
    ``tok_ids``, where ``prev_toks`` is the previous step's ``next_toks``
    output passed back UNMATERIALIZED. ``next_toks`` is a per-lane CARRY:
    lanes with ``emit_mask[b] != 0`` (the scheduler's completing lanes)
    update it to the token decided this step, everyone else passes
    ``prev_toks`` through — so a lane that skips a step (budget) still
    feeds its latest token next time. The synchronous engine passes
    all-zero ``feedback``/``prev_toks`` and the step degenerates to the
    round-9 behavior bit-for-bit. Sample keys moved ON-DEVICE with the
    same round: the host sends each lane's BASE PRNG key (``base_keys``,
    constant per request) + its tokens-produced count (``produced``) and
    the sampling branch folds them in-jit (vmapped threefry — bit-
    identical to the host-side ``fold_in`` it replaces), so a sampling
    step uploads two tiny arrays instead of deriving per-token keys on
    the host latency path.

    ``kv_quant=True`` (round 10) stores the page pools int8: the signature
    gains ``k_scales``/``v_scales`` (the per-(page-slot, head) fp32 scale
    planes, donated alongside the pools and returned updated), K/V
    quantize on write inside the step (per-token-per-head symmetric) and
    dequantize inside the ragged attention kernel — pages stay int8
    end-to-end, composing with CoW (the copy lanes duplicate scale planes
    too) and prefix caching (a shared page's scales travel with it)::

        fn(params, tok_ids, tok_slot, tok_pos, q_lens, kv_lens, last_idx,
           feedback, prev_toks, emit_mask, produced,
           k_pages, v_pages, k_scales, v_scales, page_table, cow_src,
           cow_dst, base_keys, temperature, top_k, top_p)
        -> (next_toks, logits, k_pages, v_pages, k_scales, v_scales)

    ``mesh`` (round 11) shards the whole step over ``Mesh(("mp",))`` via
    ``shard_map``: params per :func:`serving_param_specs` (qkv head-major
    — see :func:`shard_serving_params`), pools AND scale planes on the
    head axis, so quantize-on-write, the CoW lanes and the ragged
    attention kernel all run chip-local over each chip's heads — the only
    wire traffic is the two row-parallel psums per layer. Embeddings/LM
    head/logits/sampling replicate (every chip computes the identical
    epilogue). Signature, donation of all pools + scale planes, and the
    one-trace-per-geometry guarantee are unchanged.

    ``spec_k > 0`` (round 12) builds the SPECULATIVE step: a decode lane
    may feed ``1 + spec_len[slot]`` packed rows — its last context token
    followed by n-gram draft tokens (``inference/draft.py``) at the next
    positions — and the step verifies them all in the ONE ragged pass
    (per-row causal limits make row i attend the just-written K/V of rows
    < i). The signature gains ``spec_len[b]`` after ``last_idx`` (0 = the
    lane speculates nothing this step — adaptive k varies VALUES, never
    the shape) and ``last_idx`` becomes the lane's FIRST verify row (for
    a plain/prefill lane that is its last packed row, unchanged meaning).
    ``base_keys`` stays ``[b, 2]``: verify row j folds ``produced + j``
    in-jit, so the per-request seeded streams stay bit-identical to
    plain decode. The fused accept epilogue computes logits at rows
    ``last_idx .. last_idx+spec_k``, samples each (greedy argmax on
    temperature-0 lanes, bit-identical to the plain step), and accepts
    drafts while ``draft[i] == sampled[i-1]`` — returning::

        -> (out_ids[b, spec_k+1], n_emit[b], next_toks[b], logits[b,v],
            k_pages, v_pages[, k_scales, v_scales])

    where ``next_toks`` is the same per-lane carry as the plain build
    (an emitting lane carries its LAST emitted token,
    ``out_ids[b, n_emit-1]``).

    where each lane's first ``n_emit`` tokens of ``out_ids`` are its
    emissions this step (accepted prefix + one bonus token; always >= 1
    for a completing lane). Rejected drafts' K/V sits above the advanced
    watermark — the scheduler rolls their pages back host-side
    (``KVCacheManager.trim_pages``). ``spec_k`` is geometry: one trace
    per (budget, batch, spec_k), composing with ``kv_quant`` and ``mesh``
    (the epilogue replicates; donation covers the same pools).

    ``mega=True`` (round 16) builds the MEGAKERNELIZED step: the per-op
    layer chain (qkv quant-GEMM -> ragged paged attention -> output GEMM
    -> fused MLP, each a separate kernel with activations round-tripping
    HBM between them) is replaced by the two persistent per-layer Pallas
    kernels of ``ops/pallas/mega_decode`` — ``mega_attn_layer`` (LN1 +
    QKV projection + inline int8 quantize of the new K/V rows + ragged
    paged attention + output GEMM + residual + LN2, activations pinned in
    VMEM) and ``mega_mlp`` (GEMM1 + gelu + GEMM2 + residual, the 4h
    hidden state never materializing in HBM). The new K/V rows the
    attention kernel emits (int8 payloads + scale rows on the quantized
    path — quantized IN-KERNEL with the exact ``paged_write_packed_quant``
    formula) scatter into the donated pools via
    ``paged_write_packed(_prequant)``. Signature, donation, feedback,
    spec verify rows and the one-trace-per-geometry contract are all
    UNCHANGED. Round 22: the kernels serve the MIXED ragged-chunk
    geometry (any 1..chunk rows per lane), so callers build mega at the
    SAME ``(token_budget, chunk)`` geometry as the per-op step and route
    EVERY round here — no prefill fallback, no second program. Under an
    mp mesh the kernels run with ``fuse_epilogue=False`` (pre-psum
    partials) and this builder completes ``psum -> bias -> residual ->
    LN`` with the per-op spelling — the same two collectives per layer.
    ``validate_mega_config`` rejects int4 weights at build time.
    """
    import jax
    import jax.numpy as jnp

    from ..inference.kv_cache import (paged_copy_pages, paged_write_packed,
                                      paged_write_packed_prequant,
                                      paged_write_packed_quant)
    from ..ops.pallas.paged_attention import ragged_paged_attention

    cfg = config
    eps = cfg.layer_norm_eps
    trace_count = [0]
    mp, axis = _mesh_mp(mesh)
    nh_l, hd = cfg.num_heads // mp, cfg.head_dim
    if mega:
        from ..ops.pallas.mega_decode import (mega_attn_layer, mega_mlp,
                                              validate_mega_config)

        validate_mega_config(getattr(cfg, "weight_dtype", None),
                             getattr(cfg, "weight_quant_group_size", -1),
                             hd, mp,
                             moe_experts=getattr(cfg, "moe_experts", 0))
        # mp == 1: residual + LN2 / + b2 fuse INSIDE the kernels. mp > 1:
        # the kernels emit pre-psum partials and the block completes the
        # epilogue after the row-parallel psum — per-op spelling, same
        # two collectives per layer
        fuse_mega = mp == 1

    # argument layout (shared by the wrappers, shard_map specs and the
    # donation indices): params + 6 packed/lane arrays [+ spec_len] + the
    # 4 feedback arrays (feedback mask, prev_toks carry, emit_mask,
    # produced), then the donated pools [+ scale planes], then the
    # 7-array tail
    n_lead = 12 if spec_k else 11
    n_pool = 4 if kv_quant else 2
    n_out_lead = 4 if spec_k else 2

    def _body(*args):
        lead = args[:n_lead]
        pools = args[n_lead:n_lead + n_pool]
        (page_table, cow_src, cow_dst, base_keys, temperature, top_k,
         top_p) = args[n_lead + n_pool:]
        spec_len = lead[7] if spec_k else None
        feedback, prev_toks, emit_mask, produced = lead[n_lead - 4:]
        k_scales, v_scales = (pools[2], pools[3]) if kv_quant else (None,
                                                                    None)
        return _step_inner(*lead[:7], spec_len, feedback, prev_toks,
                           emit_mask, produced, pools[0], pools[1],
                           k_scales, v_scales, page_table, cow_src,
                           cow_dst, base_keys, temperature, top_k, top_p)

    def step(*args):
        trace_count[0] += 1
        body = _body
        if mesh is not None:
            from jax.sharding import PartitionSpec as P

            kv_spec, sc_spec = _kv_specs()
            rep = P()
            pool_specs = ((kv_spec, kv_spec, sc_spec, sc_spec) if kv_quant
                          else (kv_spec, kv_spec))
            body = jax.shard_map(
                _body, mesh=mesh,
                in_specs=(serving_param_specs(args[0]),)
                + (rep,) * (n_lead - 1) + pool_specs + (rep,) * 7,
                out_specs=(rep,) * n_out_lead + pool_specs,
                check_vma=False)
        # MXU-native matmul precision — see build_prefill
        with jax.default_matmul_precision("default"):
            return body(*args)

    def _step_inner(params, tok_ids, tok_slot, tok_pos, q_lens, kv_lens,
                    last_idx, spec_len, feedback, prev_toks, emit_mask,
                    produced, k_pages, v_pages, k_scales, v_scales,
                    page_table, cow_src, cow_dst, base_keys, temperature,
                    top_k, top_p):
        t = tok_ids.shape[0]
        b = q_lens.shape[0]
        # copy-on-write BEFORE any write: diverging lanes get a private
        # copy of their shared tail page across every layer (scale planes
        # are page-keyed, so they ride the same copy lanes)
        k_pages = paged_copy_pages(k_pages, cow_src, cow_dst)
        v_pages = paged_copy_pages(v_pages, cow_src, cow_dst)
        if kv_quant:
            k_scales = paged_copy_pages(k_scales, cow_src, cow_dst)
            v_scales = paged_copy_pages(v_scales, cow_src, cow_dst)
        valid = tok_slot >= 0
        slot_c = jnp.clip(tok_slot, 0, b - 1)
        # device-resident feedback: tokens the host scheduled before
        # materializing their value read the previous step's carry —
        # the async engine's device-side half of the pipeline
        tok_ids = jnp.where((feedback > 0) & valid, prev_toks[slot_c],
                            tok_ids)
        x = (jnp.take(params["tok_emb"], jnp.maximum(tok_ids, 0), axis=0)
             + params["pos_emb"][
                 jnp.clip(tok_pos, 0, params["pos_emb"].shape[0] - 1)])
        ctx = (kv_lens + q_lens).astype(jnp.int32)
        # packed <-> chunk-block index plumbing (shared by every layer):
        # each token's row in the attention kernel's [b, chunk] blocks
        off = tok_pos - kv_lens[slot_c]              # position in chunk
        off_c = jnp.clip(off, 0, chunk - 1)
        scatter_b = jnp.where(valid, tok_slot, b)    # b = dropped row

        def block(x, layer):
            if kv_quant:
                p, kp, vp, ks, vs = layer
            else:
                p, kp, vp = layer
                ks = vs = None
            y = _srv_ln(x, p["ln1_g"], p["ln1_b"], eps)
            qkv = _srv_mm(y, p["wqkv"], use_kernel) + p["bqkv"]
            q, k_t, v_t = _split_qkv(qkv, nh_l, hd,
                                     head_major=mesh is not None)
            if kv_quant:
                kp, ks = paged_write_packed_quant(
                    kp, ks, k_t, page_table, tok_slot, tok_pos, page_size)
                vp, vs = paged_write_packed_quant(
                    vp, vs, v_t, page_table, tok_slot, tok_pos, page_size)
            else:
                kp = paged_write_packed(kp, k_t, page_table, tok_slot,
                                        tok_pos, page_size)
                vp = paged_write_packed(vp, v_t, page_table, tok_slot,
                                        tok_pos, page_size)
            qb = jnp.zeros((b, chunk, nh_l, hd), q.dtype
                           ).at[scatter_b, off_c].set(q, mode="drop")
            ab = ragged_paged_attention(qb, kp, vp, page_table, ctx, q_lens,
                                        use_kernel=use_kernel,
                                        k_scales=ks, v_scales=vs)
            a = ab[slot_c, off_c]                    # back to packed [t]
            x = x + _srv_psum(_srv_mm(a.reshape(t, nh_l * hd), p["wo"],
                                      use_kernel), axis) + p["bo"]
            x = x + _srv_ffn(cfg, p, _srv_ln(x, p["ln2_g"], p["ln2_b"],
                                             eps),
                             use_kernel, axis, valid=valid)
            return x, ((kp, vp, ks, vs) if kv_quant else (kp, vp))

        def mega_block(xb, layer):
            # the round-16 fused layer (round 22: ragged chunks, any
            # 1..chunk rows per lane): the whole attention side is ONE
            # kernel over the [b, chunk] lane blocks (attention reads the
            # pool at kv_lens and handles this step's rows in-register —
            # same math as write-then-attend at ctx), the MLP side one
            # more; only the emitted new K/V rows touch HBM between them
            if kv_quant:
                p, kp, vp, ks, vs = layer
            else:
                p, kp, vp = layer
                ks = vs = None
            h = xb.shape[-1]
            res = mega_attn_layer(xb, p, kp, vp, page_table, kv_lens,
                                  q_lens, eps=eps, k_scales=ks,
                                  v_scales=vs,
                                  head_major=mesh is not None,
                                  use_kernel=use_kernel,
                                  fuse_epilogue=fuse_mega)
            if fuse_mega:
                if kv_quant:
                    y2, s, k_new, v_new, k_sc, v_sc = res
                else:
                    y2, s, k_new, v_new = res
            else:
                # mp > 1: the kernel emitted this shard's pre-psum
                # output-GEMM partial; finish the epilogue with the
                # per-op spelling (one psum, then bias/residual/LN2)
                if kv_quant:
                    y_part, k_new, v_new, k_sc, v_sc = res
                else:
                    y_part, k_new, v_new = res
                s = xb + _srv_psum(y_part, axis) + p["bo"]
                y2 = _srv_ln(s, p["ln2_g"], p["ln2_b"], eps)
            if kv_quant:
                # the kernel quantized inline — scatter the int8 payloads
                # and their scale rows (the packed gather reads each
                # token's row out of its lane block)
                kp, ks = paged_write_packed_prequant(
                    kp, ks, k_new[slot_c, off_c], k_sc[slot_c, off_c],
                    page_table, tok_slot, tok_pos, page_size)
                vp, vs = paged_write_packed_prequant(
                    vp, vs, v_new[slot_c, off_c], v_sc[slot_c, off_c],
                    page_table, tok_slot, tok_pos, page_size)
            else:
                kp = paged_write_packed(kp, k_new[slot_c, off_c],
                                        page_table, tok_slot, tok_pos,
                                        page_size)
                vp = paged_write_packed(vp, v_new[slot_c, off_c],
                                        page_table, tok_slot, tok_pos,
                                        page_size)
            if fuse_mega:
                out = mega_mlp(y2.reshape(b * chunk, h),
                               s.reshape(b * chunk, h), p,
                               use_kernel=use_kernel, chunk=chunk)
            else:
                part = mega_mlp(y2.reshape(b * chunk, h), None, p,
                                use_kernel=use_kernel,
                                fuse_epilogue=False, chunk=chunk)
                out = (s.reshape(b * chunk, h)
                       + (_srv_psum(part, axis) + p["b2"]))
            return (out.reshape(b, chunk, h),
                    ((kp, vp, ks, vs) if kv_quant else (kp, vp)))

        if mega:
            # lane-block layout for the fused layers: packed tokens
            # scatter into their [b, chunk] rows once, stay blocked
            # through every layer, and gather back for the epilogue
            carry0 = jnp.zeros((b, chunk, x.shape[-1]), x.dtype
                               ).at[scatter_b, off_c].set(x, mode="drop")
            body = mega_block
        else:
            carry0, body = x, block
        if kv_quant:
            x, (k_pages, v_pages, k_scales, v_scales) = jax.lax.scan(
                body, carry0, (params["layers"], k_pages, v_pages,
                               k_scales, v_scales))
        else:
            x, (k_pages, v_pages) = jax.lax.scan(
                body, carry0, (params["layers"], k_pages, v_pages))
        if mega:
            x = x[slot_c, off_c]                     # back to packed [t]
        x = _srv_ln(x, params["lnf_g"], params["lnf_b"], eps)
        if spec_k:
            # -- speculative verify + fused accept epilogue --------------
            # rows last_idx .. last_idx+spec_k are the lane's verify rows
            # (its last context token, then its packed draft tokens); a
            # non-speculating lane has spec_len 0 and only row 0 matters
            k1 = spec_k + 1
            rows = last_idx[:, None] + jnp.arange(k1)[None]     # [b, k1]
            rows_c = jnp.clip(rows, 0, t - 1)
            h_rows = x[rows_c]                                  # [b,k1,h]
            logits_rows = _srv_logits(params, h_rows).astype(jnp.float32)
            greedy = jnp.argmax(logits_rows, -1).astype(jnp.int32)
            v = logits_rows.shape[-1]

            def _samp():
                # row j of a lane samples with the base key folded by
                # tokens-produced + j — the on-device spelling of the
                # former host-side fold_in (vmapped threefry, bit-
                # identical), so the per-request stream matches plain
                # seeded decode
                keys = jax.vmap(
                    lambda bk, p: jax.vmap(jax.random.fold_in,
                                           in_axes=(None, 0))(
                        bk, p + jnp.arange(k1)))(base_keys, produced)
                rep = lambda a: jnp.repeat(a, k1)  # noqa: E731
                return _sample_epilogue(
                    logits_rows.reshape(b * k1, v),
                    keys.reshape(b * k1, 2), rep(temperature), rep(top_k),
                    rep(top_p)).reshape(b, k1)

            sampled = jax.lax.cond(jnp.any(temperature > 0.0), _samp,
                                   lambda: greedy)
            out_ids = jnp.where((temperature > 0.0)[:, None], sampled,
                                greedy)
            # accept while draft i matches the token the model actually
            # emits at its position: drafts ride the packed token stream
            drafts = tok_ids[jnp.clip(rows[:, 1:], 0, t - 1)]   # [b, k]
            ok = ((drafts == out_ids[:, :spec_k])
                  & (jnp.arange(spec_k)[None] < spec_len[:, None]))
            n_emit = (1 + jnp.cumprod(ok.astype(jnp.int32), axis=1).sum(1)
                      ).astype(jnp.int32)
            # per-lane carry: an emitting lane's LAST emitted token
            last_emit = jnp.take_along_axis(
                out_ids, jnp.maximum(n_emit - 1, 0)[:, None], axis=1)[:, 0]
            next_toks = jnp.where(emit_mask > 0, last_emit, prev_toks)
            if kv_quant:
                return (out_ids, n_emit, next_toks, logits_rows[:, 0],
                        k_pages, v_pages, k_scales, v_scales)
            return (out_ids, n_emit, next_toks, logits_rows[:, 0],
                    k_pages, v_pages)
        # each slot's LAST packed token yields its next-token decision
        h_last = x[jnp.clip(last_idx, 0, t - 1)]                  # [b, h]
        logits = _srv_logits(params, h_last).astype(jnp.float32)
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        # the epilogue's [b, vocab] sort/softmax/cumsum (and the key
        # folds) only EXECUTE on steps where some lane actually samples —
        # all-greedy steps (the flagship greedy serving loop) pay just
        # the argmax + predicate
        def _samp():
            keys = jax.vmap(jax.random.fold_in)(base_keys, produced)
            return _sample_epilogue(logits, keys, temperature, top_k,
                                    top_p)

        sampled = jax.lax.cond(jnp.any(temperature > 0.0), _samp,
                               lambda: greedy)
        next_ids = jnp.where(temperature > 0.0, sampled, greedy)
        # per-lane carry: emitting lanes refresh, everyone else passes
        # the previous token through (a lane skipped by the budget still
        # feeds its latest token through feedback next step)
        next_toks = jnp.where(emit_mask > 0, next_ids, prev_toks)
        if kv_quant:
            return (next_toks, logits, k_pages, v_pages, k_scales,
                    v_scales)
        return next_toks, logits, k_pages, v_pages

    jitted = jax.jit(step,
                     donate_argnums=tuple(range(n_lead, n_lead + n_pool)))
    jitted.trace_count = trace_count
    return jitted


# generate_paged's compiled programs, keyed by (config fields, page_size,
# use_kernel): repeated generate() calls replay the same jit instead of
# re-tracing + re-compiling the whole model each call. ServingPredictor
# holds its own per-instance pair (its trace counter is a per-predictor
# gate), so only the convenience path shares.
_SERVING_JIT_CACHE: dict = {}

# per-model extracted params (the [L, ...] stacks are device copies):
# weak-keyed so a collected model drops its stacks, id-validated so an
# optimizer step (which rebinds every ._data) forces re-extraction
import weakref as _weakref  # noqa: E402

_SERVING_PARAMS_CACHE = _weakref.WeakKeyDictionary()


def _quant_sig(cfg: GPTConfig):
    """The config fields that change what _serving_params_cached extracts
    (a flipped weight_dtype must invalidate the cached fp pytree even
    though the underlying buffers are unchanged)."""
    return (getattr(cfg, "weight_dtype", None),
            getattr(cfg, "weight_quant_group_size", -1))


def _serving_params_cached(model, mesh=None):
    # staleness check by buffer IDENTITY against WEAKLY-held capture-time
    # buffers: identity comparison is immune to CPython id reuse, and the
    # weakrefs mean an optimizer step's rebinding doesn't leave ~1x model
    # weights of dead buffers pinned by the cache key (a dead ref simply
    # reads as stale). Round 11: the cached value is a per-MESH-SIGNATURE
    # dict (None = the unsharded extraction; every sharded layout derives
    # from it), so two mesh sizes neither collide nor evict each other.
    from ..distributed.mesh import mesh_signature

    cfg = (model.gpt if hasattr(model, "gpt") else model).config
    qsig = _quant_sig(cfg)
    msig = mesh_signature(mesh)
    bufs = _serving_weight_buffers(model)
    hit = _SERVING_PARAMS_CACHE.get(model)
    if (hit is not None and len(hit[0]) == len(bufs)
            and hit[2] == qsig
            and all(ref() is cur for ref, cur in zip(hit[0], bufs))):
        by_mesh = hit[1]
    else:
        by_mesh = {}
        try:
            _SERVING_PARAMS_CACHE[model] = (
                [_weakref.ref(b) for b in bufs], by_mesh, qsig)
        except TypeError:
            pass  # un-weakrefable model object: just skip the cache
    if None not in by_mesh:
        params = serving_params(model)
        if cfg.weight_dtype is not None:
            from ..inference.quantize import quantize_serving_params

            params = quantize_serving_params(
                params, cfg.weight_dtype, cfg.weight_quant_group_size)
        by_mesh[None] = params
    if msig is None:
        return by_mesh[None]
    if msig not in by_mesh:
        by_mesh[msig] = shard_serving_params(by_mesh[None], mesh, cfg)
    return by_mesh[msig]


def _jit_cache_get(key, build):
    hit = _SERVING_JIT_CACHE.get(key)
    if hit is None:
        # bounded LRU (same policy as the engine's eager-op cache): a
        # process sweeping geometries must not pin executables forever
        while len(_SERVING_JIT_CACHE) >= 32:
            _SERVING_JIT_CACHE.pop(next(iter(_SERVING_JIT_CACHE)))
        hit = build()
    else:
        _SERVING_JIT_CACHE.pop(key)  # refresh recency
    _SERVING_JIT_CACHE[key] = hit
    return hit


def _cfg_key(config: GPTConfig):
    import dataclasses

    return tuple((f.name, getattr(config, f.name))
                 for f in dataclasses.fields(config))


def _serving_fns(config: GPTConfig, page_size: int, use_kernel, mesh=None):
    from ..distributed.mesh import mesh_signature

    return _jit_cache_get(
        ("legacy", _cfg_key(config), page_size, use_kernel,
         mesh_signature(mesh)),
        lambda: (build_prefill(config, page_size,
                               use_kernel=use_kernel, mesh=mesh),
                 build_decode_step(config, page_size,
                                   use_kernel=use_kernel, mesh=mesh)))


def _unified_fn(config: GPTConfig, page_size: int, chunk: int, use_kernel,
                kv_quant=False, mesh=None, spec_k=0, mega=False):
    # the mesh SIGNATURE keys the cache (satellite of round 11): two mesh
    # sizes get two entries — neither collides with nor retraces the other.
    # spec_k is build GEOMETRY (the [b, k+1] output): two k values get two
    # executables, each compiled once; adaptive per-request k never keys.
    # mega (round 16) keys too: the megakernelized decode build and the
    # per-op build coexist — the scheduler routes rounds between them
    from ..distributed.mesh import mesh_signature

    return _jit_cache_get(
        ("unified", _cfg_key(config), page_size, chunk, use_kernel,
         kv_quant, mesh_signature(mesh), spec_k, mega),
        lambda: build_unified_step(config, page_size, chunk,
                                   use_kernel=use_kernel,
                                   kv_quant=kv_quant, mesh=mesh,
                                   spec_k=spec_k, mega=mega))


# ---------------------------------------------------------------------------
# Round-19 model-based self-draft: the draft "model" is the first
# ``draft_layers`` decoder layers of the SAME serving stack (shared
# embeddings / positional table / final LN / LM head — zero extra weights
# to load; a distinct EAGLE-style draft param pytree can ride the same
# surface later by swapping what draft_serving_params returns). The draft
# pass is just the round-9 unified step built from a truncated config, so
# it inherits the packed token budget, the paged-KV write/ragged-attention
# discipline, the device-resident feedback carry (the k-token draft chain
# never materializes intermediate tokens on the host) and the
# one-trace-per-geometry contract for free.
# ---------------------------------------------------------------------------


def draft_config(config: GPTConfig, draft_layers: int) -> GPTConfig:
    """The truncated-stack config the draft jits build from. Rejects
    degenerate depths loudly: ``draft_layers >= num_layers`` would run the
    full target as its own drafter (all cost, no speedup) and is always a
    configuration mistake."""
    import dataclasses

    draft_layers = int(draft_layers)
    if draft_layers < 1:
        raise ValueError(
            f"spec_draft_layers must be >= 1, got {draft_layers}")
    if draft_layers >= config.num_layers:
        raise ValueError(
            f"spec_draft_layers {draft_layers} must be < num_layers "
            f"{config.num_layers} (a full-depth draft would run the "
            "target twice per token instead of a cheap proposer)")
    # the draft stack serves plain decode only: no nested speculation.
    # mega_decode clears here because the draft jits pick their kernel
    # family EXPLICITLY — build_draft_step stays per-op (catch-up
    # geometry), build_draft_chain takes a ``mega`` flag (round 22: the
    # fused k-step chain runs the mega blocks when the parent does)
    return dataclasses.replace(config, num_layers=draft_layers,
                               spec_decode_k=0, spec_draft_layers=0,
                               mega_decode=False)


def draft_serving_params(params, draft_layers: int):
    """Slice a serving params pytree down to the first ``draft_layers``
    scan stacks. The non-layer leaves (embeddings, final LN, LM head) are
    SHARED by reference — the self-draft loads zero extra weights; only
    the truncated layer stacks are (small) device slices. Works on fp and
    quantized (``{"q", "s"}``) stacks alike."""
    import jax

    out = {k: v for k, v in params.items() if k != "layers"}
    out["layers"] = jax.tree.map(lambda a: a[:draft_layers],
                                 params["layers"])
    return out


def build_draft_step(config: GPTConfig, draft_layers: int, page_size: int,
                     chunk: int, use_kernel=None, kv_quant: bool = False,
                     mesh=None):
    """The draft pass's fixed-shape jit: the unified serving step built
    from the TRUNCATED config (validated by :func:`draft_config`) — one
    build serves both the catch-up prefill chunks and the chunk-1 decode
    chain geometry (the caller picks ``chunk``). Shares the process-wide
    jit cache, so every predictor with the same draft geometry replays one
    executable."""
    return _unified_fn(draft_config(config, draft_layers), page_size,
                       chunk, use_kernel, kv_quant=kv_quant, mesh=mesh)


def build_draft_chain(config: GPTConfig, draft_layers: int, page_size: int,
                      k: int, use_kernel=None, kv_quant: bool = False,
                      mesh=None, mega: bool = False):
    """The WHOLE k-step draft proposal chain as ONE jit (round 22).

    The round-19 engine launched the chunk-1 draft step k times per
    round, chaining tokens through the device feedback carry — k
    dispatches, k host pack loops. This builder rolls the chain into a
    single program: a ``lax.scan`` over the k chain steps, each step the
    truncated stack at chunk-1 geometry (per-op blocks, or the round-16
    mega blocks when ``mega=True`` — one persistent kernel pair per
    layer per step, device-chained), so a speculative round costs ONE
    draft dispatch + ONE verify dispatch.

    Signature::

        fn(params, first_toks[b], steps[b], kv_lens[b],
           k_pages, v_pages[, k_scales, v_scales], page_table)
        -> (drafts[b, k], k_pages, v_pages[, k_scales, v_scales])

    ``first_toks[lane]`` is the lane's live last context token (chain
    step 0's input), ``steps[lane]`` how many chain steps the lane runs
    (0 = idle — the lane writes nothing and its drafts read 0),
    ``kv_lens[lane]`` the draft pool's watermark at chain start. Chain
    step j writes the lane's K/V at position ``kv_lens + j`` and feeds
    its greedy argmax to step j+1 — bit-identical to k separate chunk-1
    unified-step dispatches chained through the feedback carry. The
    caller pre-reserves page capacity for ``kv_lens + steps`` (the page
    table is fixed for the whole chain) and advances its host watermark
    by the steps actually run. Pools donate; the trace-count contract
    matches the unified step.
    """
    import jax
    import jax.numpy as jnp

    from ..inference.kv_cache import (paged_write_packed,
                                      paged_write_packed_prequant,
                                      paged_write_packed_quant)
    from ..ops.pallas.paged_attention import ragged_paged_attention

    cfg = draft_config(config, draft_layers)
    eps = cfg.layer_norm_eps
    trace_count = [0]
    mp, axis = _mesh_mp(mesh)
    nh_l, hd = cfg.num_heads // mp, cfg.head_dim
    k = int(k)
    if k < 1:
        raise ValueError(f"draft chain length k must be >= 1, got {k}")
    mega = bool(mega)
    if mega:
        from ..ops.pallas.mega_decode import (mega_attn_layer, mega_mlp,
                                              validate_mega_config)

        validate_mega_config(getattr(cfg, "weight_dtype", None),
                             getattr(cfg, "weight_quant_group_size", -1),
                             hd, mp,
                             moe_experts=getattr(cfg, "moe_experts", 0))
        fuse_mega = mp == 1
    n_pool = 4 if kv_quant else 2

    def _chain_inner(params, first_toks, steps, kv_lens0, *rest):
        pools0 = rest[:n_pool]
        page_table = rest[n_pool]
        b = first_toks.shape[0]
        lane = jnp.arange(b, dtype=jnp.int32)
        kv_lens0 = kv_lens0.astype(jnp.int32)

        def one_step(carry, j):
            ids, pools = carry
            if kv_quant:
                k_pages, v_pages, k_scales, v_scales = pools
            else:
                k_pages, v_pages = pools
                k_scales = v_scales = None
            active = j < steps
            q_lens = jnp.where(active, 1, 0).astype(jnp.int32)
            tok_slot = jnp.where(active, lane, -1).astype(jnp.int32)
            tok_pos = kv_lens0 + j
            kv_lens = kv_lens0 + j
            ctx = (kv_lens + q_lens).astype(jnp.int32)
            valid = tok_slot >= 0
            slot_c = jnp.clip(tok_slot, 0, b - 1)
            scatter_b = jnp.where(valid, tok_slot, b)
            x = (jnp.take(params["tok_emb"], jnp.maximum(ids, 0), axis=0)
                 + params["pos_emb"][
                     jnp.clip(tok_pos, 0,
                              params["pos_emb"].shape[0] - 1)])

            def block(x, layer):
                # the per-op layer at chunk-1 geometry — the exact
                # _step_inner spelling (one packed row per lane)
                if kv_quant:
                    p, kp, vp, ks, vs = layer
                else:
                    p, kp, vp = layer
                    ks = vs = None
                y = _srv_ln(x, p["ln1_g"], p["ln1_b"], eps)
                qkv = _srv_mm(y, p["wqkv"], use_kernel) + p["bqkv"]
                q, k_t, v_t = _split_qkv(qkv, nh_l, hd,
                                         head_major=mesh is not None)
                if kv_quant:
                    kp, ks = paged_write_packed_quant(
                        kp, ks, k_t, page_table, tok_slot, tok_pos,
                        page_size)
                    vp, vs = paged_write_packed_quant(
                        vp, vs, v_t, page_table, tok_slot, tok_pos,
                        page_size)
                else:
                    kp = paged_write_packed(kp, k_t, page_table, tok_slot,
                                            tok_pos, page_size)
                    vp = paged_write_packed(vp, v_t, page_table, tok_slot,
                                            tok_pos, page_size)
                qb = jnp.zeros((b, 1, nh_l, hd), q.dtype
                               ).at[scatter_b, 0].set(q, mode="drop")
                ab = ragged_paged_attention(qb, kp, vp, page_table, ctx,
                                            q_lens, use_kernel=use_kernel,
                                            k_scales=ks, v_scales=vs)
                a = ab[slot_c, 0]
                x = x + _srv_psum(_srv_mm(a.reshape(b, nh_l * hd),
                                          p["wo"], use_kernel),
                                  axis) + p["bo"]
                x = x + _srv_ffn(cfg, p, _srv_ln(x, p["ln2_g"],
                                                 p["ln2_b"], eps),
                                 use_kernel, axis, valid=valid)
                return x, ((kp, vp, ks, vs) if kv_quant else (kp, vp))

            def mega_block(xb, layer):
                # the fused layer at chunk-1 geometry (round 16 blocks,
                # round-22 mp composition via fuse_epilogue)
                if kv_quant:
                    p, kp, vp, ks, vs = layer
                else:
                    p, kp, vp = layer
                    ks = vs = None
                h = xb.shape[-1]
                res = mega_attn_layer(xb, p, kp, vp, page_table, kv_lens,
                                      q_lens, eps=eps, k_scales=ks,
                                      v_scales=vs,
                                      head_major=mesh is not None,
                                      use_kernel=use_kernel,
                                      fuse_epilogue=fuse_mega)
                if fuse_mega:
                    if kv_quant:
                        y2, s, k_new, v_new, k_sc, v_sc = res
                    else:
                        y2, s, k_new, v_new = res
                else:
                    if kv_quant:
                        y_part, k_new, v_new, k_sc, v_sc = res
                    else:
                        y_part, k_new, v_new = res
                    s = xb + _srv_psum(y_part, axis) + p["bo"]
                    y2 = _srv_ln(s, p["ln2_g"], p["ln2_b"], eps)
                if kv_quant:
                    kp, ks = paged_write_packed_prequant(
                        kp, ks, k_new[slot_c, 0], k_sc[slot_c, 0],
                        page_table, tok_slot, tok_pos, page_size)
                    vp, vs = paged_write_packed_prequant(
                        vp, vs, v_new[slot_c, 0], v_sc[slot_c, 0],
                        page_table, tok_slot, tok_pos, page_size)
                else:
                    kp = paged_write_packed(kp, k_new[slot_c, 0],
                                            page_table, tok_slot, tok_pos,
                                            page_size)
                    vp = paged_write_packed(vp, v_new[slot_c, 0],
                                            page_table, tok_slot, tok_pos,
                                            page_size)
                if fuse_mega:
                    out = mega_mlp(y2.reshape(b, h), s.reshape(b, h), p,
                                   use_kernel=use_kernel, chunk=1)
                else:
                    part = mega_mlp(y2.reshape(b, h), None, p,
                                    use_kernel=use_kernel,
                                    fuse_epilogue=False, chunk=1)
                    out = (s.reshape(b, h)
                           + (_srv_psum(part, axis) + p["b2"]))
                return (out.reshape(b, 1, h),
                        ((kp, vp, ks, vs) if kv_quant else (kp, vp)))

            if mega:
                carry0 = jnp.zeros((b, 1, x.shape[-1]), x.dtype
                                   ).at[scatter_b, 0].set(x, mode="drop")
                body = mega_block
            else:
                carry0, body = x, block
            if kv_quant:
                x, (k_pages, v_pages, k_scales, v_scales) = jax.lax.scan(
                    body, carry0, (params["layers"], k_pages, v_pages,
                                   k_scales, v_scales))
                pools = (k_pages, v_pages, k_scales, v_scales)
            else:
                x, (k_pages, v_pages) = jax.lax.scan(
                    body, carry0, (params["layers"], k_pages, v_pages))
                pools = (k_pages, v_pages)
            if mega:
                x = x[slot_c, 0]
            x = _srv_ln(x, params["lnf_g"], params["lnf_b"], eps)
            logits = _srv_logits(params, x).astype(jnp.float32)
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            ids_next = jnp.where(active, nxt, ids)
            return (ids_next, pools), jnp.where(active, nxt, 0)

        (_, pools), drafts = jax.lax.scan(
            one_step, (first_toks.astype(jnp.int32), pools0),
            jnp.arange(k, dtype=jnp.int32))
        return (drafts.T,) + tuple(pools)   # [b, k]

    def chain(*args):
        trace_count[0] += 1
        body = _chain_inner
        if mesh is not None:
            from jax.sharding import PartitionSpec as P

            kv_spec, sc_spec = _kv_specs()
            rep = P()
            pool_specs = ((kv_spec, kv_spec, sc_spec, sc_spec) if kv_quant
                          else (kv_spec, kv_spec))
            body = jax.shard_map(
                _chain_inner, mesh=mesh,
                in_specs=(serving_param_specs(args[0]),) + (rep,) * 3
                + pool_specs + (rep,),
                out_specs=(rep,) + pool_specs,
                check_vma=False)
        with jax.default_matmul_precision("default"):
            return body(*args)

    jitted = jax.jit(chain, donate_argnums=tuple(range(4, 4 + n_pool)))
    jitted.trace_count = trace_count
    return jitted


def _draft_chain_fn(config: GPTConfig, draft_layers: int, page_size: int,
                    k: int, use_kernel, kv_quant=False, mesh=None,
                    mega=False):
    """Process-wide jit cache for :func:`build_draft_chain` (same policy
    as ``_unified_fn``: every predictor with the same draft geometry
    replays one executable; ``k`` and ``mega`` are build geometry)."""
    from ..distributed.mesh import mesh_signature

    return _jit_cache_get(
        ("draft_chain", _cfg_key(draft_config(config, draft_layers)),
         page_size, k, use_kernel, kv_quant, mesh_signature(mesh), mega),
        lambda: build_draft_chain(config, draft_layers, page_size, k,
                                  use_kernel=use_kernel,
                                  kv_quant=kv_quant, mesh=mesh,
                                  mega=mega))


def generate_paged(model, input_ids, max_new_tokens=20, *, page_size=None,
                   num_pages=None, use_kernel=None, eos_token_id=None,
                   chunk=None, temperature=0.0, top_k=0, top_p=1.0,
                   seed=0, mesh=None, spec_decode_k=None):
    """Autoregressive generation over the paged KV cache — round 9: ONE
    unified-step jit serves prefill chunks and decode tokens alike.

    ``input_ids``: [batch, prompt_len] (Tensor or array). Prompts feed in
    ``chunk``-token ragged chunks (autotuned default), then every decode
    token replays the SAME fixed-shape program — no per-bucket prefill
    executables, no retrace after warmup. Greedy (``temperature == 0``,
    the default) is bit-identical to the round-7 two-jit path and the
    full-forward oracle. ``temperature > 0`` runs the fused seeded
    temperature/top-k/top-p epilogue (``seed`` makes it reproducible).
    With ``eos_token_id``, a row that stops early frees its cache pages,
    its lane goes inert, and its remaining columns pad with the eos id.

    Round 11: ``mesh`` (None, an int mp degree, or a ``Mesh(("mp",))``)
    serves the step tensor-parallel — params head/column-sharded, the KV
    pools and scale planes sharded by head — through the SAME scheduler
    loop; the host-side page/slot bookkeeping stays global. ``mesh=1``
    runs the sharded program on one chip, bit-identical to ``mesh=None``.

    Round 10: ``config.weight_dtype`` ("int8"/"int4") serves the decoder
    matmuls through the fused weight-only Pallas GEMM (weights stay
    quantized in HBM), and ``config.kv_cache_dtype == "int8"`` stores the
    page pools int8 with quantize-on-write + in-kernel dequant — greedy
    decoding then matches the fp oracle to within quantization noise
    (>= 99% of tokens in the smoke config) rather than bit-exactly.

    Round 12: ``spec_decode_k`` (default ``config.spec_decode_k``; > 0
    enables) runs the draft–verify–accept speculative loop: each row owns
    an n-gram/prompt-lookup :class:`~paddle_tpu.inference.draft.
    DraftProposer`, decode rounds feed ``1 + k`` verify rows through the
    SAME unified step (``spec_k`` build geometry) and emit the accepted
    prefix + one bonus token per round. Greedy output stays token-for-
    token identical to plain decode (the accept rule only keeps drafts
    the plain stream would have produced); rejected drafts' pages roll
    back via ``KVCacheManager.trim_pages``. Sampled rows key row j by
    (row, tokens-produced + j) so a seed reproduces the stream across k.
    """
    import numpy as np

    import jax
    import jax.numpy as jnp

    from ..inference.kv_cache import (KVCacheManager, kv_cache_quantized,
                                      pages_needed)
    from ..tensor.tensor import Tensor

    from ..distributed.mesh import as_serving_mesh

    mesh = as_serving_mesh(mesh)
    cfg = (model.gpt if hasattr(model, "gpt") else model).config
    ids_np = np.asarray(input_ids.numpy() if isinstance(input_ids, Tensor)
                        else input_ids).astype(np.int32)
    b, s = ids_np.shape
    if s == 0:
        raise ValueError("empty prompt")
    if max_new_tokens <= 0:
        generate_paged.last_decode_trace_count = 0
        return Tensor(jnp.zeros((b, 0), jnp.int64))
    total = s + max_new_tokens
    if total > cfg.max_seq_len:
        raise ValueError(
            f"prompt {s} + max_new_tokens {max_new_tokens} exceeds "
            f"max_seq_len {cfg.max_seq_len}")
    params = _serving_params_cached(model, mesh=mesh)
    dtype = params["tok_emb"].dtype
    if page_size is None or chunk is None:
        from ..ops.pallas.paged_attention import (preferred_chunk_size,
                                                  preferred_page_size)

        if page_size is None:
            page_size = preferred_page_size(cfg.num_heads, cfg.num_heads,
                                            cfg.head_dim, dtype)
        if chunk is None:
            chunk = preferred_chunk_size(cfg.num_heads, cfg.num_heads,
                                         cfg.head_dim, dtype)
    kv_quant = kv_cache_quantized(cfg.kv_cache_dtype)
    mgr = KVCacheManager(
        cfg.num_layers, cfg.num_heads, cfg.head_dim,
        num_pages=num_pages or b * pages_needed(total, page_size),
        max_batch=b, max_seq_len=total, page_size=page_size, dtype=dtype,
        quantize_kv=kv_quant, mesh=mesh)
    contexts = [[int(t) for t in row] for row in ids_np]
    slots: list = []
    for ctx in contexts:
        slot, _ = mgr.admit_prefix(ctx)   # no prefix sharing here: the
        slots.append(slot)                # ServingPredictor owns that path

    chunk = int(chunk)
    spec_k = int(cfg.spec_decode_k if spec_decode_k is None
                 else (spec_decode_k or 0))
    if spec_k < 0:
        raise ValueError(f"spec_decode_k must be >= 0, got {spec_k}")
    if spec_k and spec_k >= chunk:
        raise ValueError(
            f"spec_decode_k {spec_k} needs 1 + k <= chunk {chunk} (the "
            "verify rows ride the per-slot chunk block)")
    proposers = None
    if spec_k:
        from ..inference.draft import DraftProposer

        proposers = [DraftProposer(spec_k) for _ in range(b)]
    # round 22: with mega_decode on, the ONE unified program IS the
    # megakernelized build — the fused kernels serve the mixed ragged-
    # chunk geometry (any 1..chunk rows per lane), so prefill chunks and
    # decode rounds alike run the same fixed-shape mega program (the
    # round-16 per-op fallback + round-content router are gone)
    step = _unified_fn(cfg, mgr.page_size, chunk, use_kernel,
                       kv_quant=kv_quant, mesh=mesh, spec_k=spec_k,
                       mega=bool(getattr(cfg, "mega_decode", False)))
    traces_at_entry = step.trace_count[0]
    # token budget: every row can feed a full chunk each round (generate
    # drives all rows in lockstep; the budget-packed scheduler lives in
    # ServingPredictor). constant per-call sampling plumbing; generate
    # never shares pages, so copy-on-write stays on the no-op sentinel
    t_budget = b * chunk
    no_cow = jnp.full((b,), mgr.num_pages, jnp.int32)
    temp_arr = jnp.full((b,), float(temperature), jnp.float32)
    topk_arr = jnp.full((b,), int(top_k), jnp.int32)
    topp_arr = jnp.full((b,), float(top_p), jnp.float32)
    # the synchronous convenience loop never defers emission: feedback
    # stays all-zero and the carry input is a constant (no upload)
    no_feedback = jnp.zeros((t_budget,), jnp.int32)
    zero_prev = jnp.zeros((b,), jnp.int32)
    base_keys = jnp.zeros((b, 2), jnp.uint32)
    if temperature > 0:
        # one vectorized fold per CALL for the per-row base keys; the
        # per-token keys fold IN-JIT from (base key, tokens produced) —
        # vmapped threefry, bit-identical to the former host-side folds
        base_key = jax.random.PRNGKey(int(seed))
        base_keys = jnp.asarray(np.asarray(
            jax.vmap(jax.random.fold_in, in_axes=(None, 0))(
                base_key, jnp.arange(b)), np.uint32))

    outs: list[list[int]] = [[] for _ in range(b)]
    done = np.zeros((b,), bool)
    while not done.all():
        # free ALL finished lanes first (their lane goes inert), THEN grow
        # the live ones: a tight pool must see the reclaimed pages before
        # any capacity check can fail
        for i, sl in enumerate(slots):
            if done[i] and sl is not None:
                mgr.free(sl)
                slots[i] = None
        t_route, fn, fb = t_budget, step, no_feedback
        q_lens = np.zeros((b,), np.int32)
        tok_ids = np.zeros((t_route,), np.int32)
        tok_slot = np.full((t_route,), -1, np.int32)
        tok_pos = np.zeros((t_route,), np.int32)
        last_idx = np.full((b,), t_route, np.int32)   # idle sentinel
        spec_len = np.zeros((b,), np.int32)
        emit_mask = np.zeros((b,), np.int32)
        produced = np.zeros((b,), np.int32)
        if spec_k:
            # pages every live row will claim for its PLAIN tokens this
            # round, charged against draft allowances (the serving-path
            # reservation): drafts stay opportunistic — a pool an eos-
            # stopping plain run fits must never crash under speculation
            plain_need = {
                sl: mgr.plain_step_page_need(
                    sl, min(chunk, len(contexts[i]) - mgr.seq_len(sl)))
                for i, sl in enumerate(slots)
                if sl is not None and not done[i]}
            pending_need = sum(plain_need.values())
        w = 0
        for i, sl in enumerate(slots):
            if sl is None or done[i]:
                continue
            if spec_k:
                pending_need -= plain_need.pop(sl, 0)
            written = mgr.seq_len(sl)
            remaining = len(contexts[i]) - written
            d: list[int] = []
            if spec_k and remaining == 1:
                # decode round: draft up to k tokens, clamped so emission
                # can't overshoot the output budget (a lane one token from
                # done drafts nothing) and so drafts only claim pages no
                # live row needs for its plain tokens
                room = min(spec_k, max_new_tokens - len(outs[i]) - 1,
                           mgr.draft_allowance(sl, reserve=pending_need))
                if room > 0:
                    d = proposers[i].propose(contexts[i], room)
            n = (1 + len(d)) if d else min(chunk, remaining)
            if not mgr.ensure_capacity(sl, written + n):
                # an undersized pool must fail loudly: the dropped K/V
                # write would otherwise silently corrupt every later token
                raise RuntimeError(
                    f"KV cache exhausted growing slot {sl} to "
                    f"{written + n} tokens — pass a larger "
                    "num_pages (or use ServingPredictor, which preempts)")
            q_lens[sl] = n
            tok_ids[w:w + n] = (([contexts[i][written]] + d) if d
                                else contexts[i][written:written + n])
            tok_slot[w:w + n] = sl
            tok_pos[w:w + n] = np.arange(written, written + n)
            # the row whose logits decide the next token: the FIRST verify
            # row for a speculating lane, the last fed row otherwise
            last_idx[sl] = w + n - 1 - len(d)
            spec_len[sl] = len(d)
            if written + n - len(d) == len(contexts[i]):
                # this chunk reaches the context end: the lane emits.
                # sampling row j folds (base key, produced + j) IN-JIT —
                # keying by tokens PRODUCED (the ServingPredictor
                # convention) makes the sampled stream identical across
                # every spec k, including k = 0: speculation changes
                # cost, never output
                emit_mask[sl] = 1
                produced[sl] = len(outs[i])
            w += n
        packed = (params, jnp.asarray(tok_ids), jnp.asarray(tok_slot),
                  jnp.asarray(tok_pos), jnp.asarray(q_lens),
                  mgr.seq_lens_device(), jnp.asarray(last_idx))
        if spec_k:
            packed = packed + (jnp.asarray(spec_len),)
        packed = packed + (fb, zero_prev, jnp.asarray(emit_mask),
                           jnp.asarray(produced))
        tail = (mgr.page_table_device(), no_cow, no_cow, base_keys,
                temp_arr, topk_arr, topp_arr)
        pools = ((mgr.k_pages, mgr.v_pages, mgr.k_scales, mgr.v_scales)
                 if kv_quant else (mgr.k_pages, mgr.v_pages))
        res = fn(*packed, *pools, *tail)
        if spec_k:
            out_ids, n_emit = np.asarray(res[0]), np.asarray(res[1])
            mgr.update_pages(*res[4:])
        else:
            out_ids, n_emit = np.asarray(res[0]), None
            mgr.update_pages(*res[2:])
        for i, sl in enumerate(slots):
            if sl is None or q_lens[sl] == 0:
                continue
            if spec_len[sl]:
                # speculative round: 1 + accepted tokens are valid; the
                # rejected drafts' over-allocated pages roll back
                m = int(n_emit[sl])
                mgr.advance(sl, m)
                mgr.trim_pages(sl)
                emitted = [int(t) for t in out_ids[sl, :m]]
                proposers[i].update(int(spec_len[sl]), m - 1)
            else:
                mgr.advance(sl, int(q_lens[sl]))
                if mgr.seq_len(sl) < len(contexts[i]):
                    continue   # mid-prefill round: nothing emitted yet
                emitted = [int(out_ids[sl, 0] if spec_k else out_ids[sl])]
                if spec_k:
                    proposers[i].update(0, 0)
            for tok in emitted:
                if done[i]:
                    break   # budget/eos hit mid-batch: drop the overhang
                outs[i].append(tok)
                contexts[i].append(tok)
                if eos_token_id is not None and tok == eos_token_id:
                    done[i] = True
                if len(outs[i]) >= max_new_tokens:
                    done[i] = True
    # traces THIS call added: 1 on a cold shape, 0 when the cached jit
    # already compiled it — never per-token (the no-retrace gate). With
    # mega_decode on, the mega build IS the one program (round 22)
    generate_paged.last_decode_trace_count = (step.trace_count[0]
                                              - traces_at_entry)
    # rows that stopped early (eos) pad with the eos id, as before
    n_cols = max(len(o) for o in outs)
    pad = eos_token_id if eos_token_id is not None else 0
    arr = np.full((b, n_cols), pad, np.int64)
    for i, o in enumerate(outs):
        arr[i, :len(o)] = o
    return Tensor(jnp.asarray(arr, jnp.int64))
