"""GPT model family — the flagship benchmark model.

Architecture parity: the reference's fleet GPT test models
(test/collective/fleet/hybrid_parallel_pp_transformer.py,
hybrid_parallel_mp_model.py) and the GPT-3 paper sizes named in BASELINE.md.
Pre-LN decoder blocks, learned positional embeddings, GELU MLP (4x), causal
self-attention through ``F.scaled_dot_product_attention`` (flash-attention
Pallas kernel on TPU when available).

Tensor parallelism: with ``mp_degree > 1`` (or fleet initialised), qkv/out and
mlp projections become Column/RowParallelLinear and the token embedding
VocabParallelEmbedding — the Megatron layout (reference: fleet/layers/mpu/
mp_layers.py:47,:333,:540) where GSPMD emits the collectives.
"""
from __future__ import annotations

from dataclasses import dataclass

from ..framework.param_attr import ParamAttr
from ..nn import Layer, functional as F
from ..nn.initializer import Normal
from ..nn.layer.common import Dropout, Embedding, Linear
from ..nn.layer.container import LayerList
from ..nn.layer.norm import LayerNorm
from ..tensor.creation import arange
from ..tensor.manipulation import concat, reshape
from ..tensor.math import matmul


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    max_seq_len: int = 1024
    intermediate_size: int | None = None  # default 4*hidden
    hidden_dropout: float = 0.0
    attn_dropout: float = 0.0
    layer_norm_eps: float = 1e-5
    initializer_range: float = 0.02
    tie_word_embeddings: bool = True
    use_flash_attention: bool = True
    # run the Pallas kernel in interpret mode off-TPU too (CPU-mesh tests of
    # the sharded kernel path; never set in production configs)
    force_flash: bool = False
    # fused MLP-block Pallas kernels (ops/pallas/fused_mlp): single-pass
    # LN (+ residual-in/out) and bias+gelu epilogues replace the XLA
    # elementwise chains in the decoder block — the round-5 roofline's
    # ~20 ms/step of LN/gelu/residual HBM round-trips. bench.py flips this
    # via --fused-mlp; off by default until the on-chip A/B confirms it.
    fused_mlp: bool = False
    # run the fused MLP kernels in interpret mode off-TPU too (CPU tests)
    force_fused_mlp: bool = False
    # parallel knobs
    tensor_parallel: bool = False  # force TP layers even without fleet
    recompute: bool = False  # rematerialize blocks in backward (activation
    # memory ~O(layers*s*h) instead of O(layers*s*4h stacks))
    remat_save_attn: bool = True  # under recompute, also save the flash
    # kernel's o/lse (backward skips the attention re-forward for
    # ~layers*s*h*2B extra residency); memory-edge configs (1.3B on 16 GB)
    # set False to keep the smaller footprint
    remat_save_ln: bool = False  # under recompute, also save both LN
    # outputs per layer (2*layers*s*h*2B extra residency, ~1.2 GB at 760M
    # bs8): backward skips the LN re-forward (mean/var/normalize passes)
    # perf-attribution ablations (perf_breakdown.py only — differential
    # timing of step phases; never set in training configs): any of
    # {"attn", "mlp", "ce"} ("ce" keeps the lm-head matmul, drops the
    # softmax-CE math)
    ablate: tuple = ()

    @property
    def ffn_size(self) -> int:
        return self.intermediate_size or 4 * self.hidden_size

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    def num_params(self) -> int:
        h, v, l = self.hidden_size, self.vocab_size, self.num_layers
        per_layer = 4 * h * h + 4 * h + 2 * h * self.ffn_size + h + self.ffn_size + 4 * h
        emb = v * h + self.max_seq_len * h
        return emb + l * per_layer + 2 * h


# GPT-3 paper table 2.1 sizes (the BASELINE.md benchmark ladder).
GPT_CONFIGS: dict[str, GPTConfig] = {
    "gpt3-tiny": GPTConfig(vocab_size=1024, hidden_size=128, num_layers=2, num_heads=4, max_seq_len=128),
    "gpt3-125m": GPTConfig(hidden_size=768, num_layers=12, num_heads=12),
    "gpt3-350m": GPTConfig(hidden_size=1024, num_layers=24, num_heads=16),
    "gpt3-760m": GPTConfig(hidden_size=1536, num_layers=24, num_heads=16),
    "gpt3-1.3b": GPTConfig(hidden_size=2048, num_layers=24, num_heads=32, max_seq_len=2048),
    "gpt3-2.7b": GPTConfig(hidden_size=2560, num_layers=32, num_heads=32, max_seq_len=2048),
    "gpt3-6.7b": GPTConfig(hidden_size=4096, num_layers=32, num_heads=32, max_seq_len=2048),
    "gpt3-13b": GPTConfig(hidden_size=5120, num_layers=40, num_heads=40, max_seq_len=2048),
}


def _w(config: GPTConfig) -> ParamAttr:
    """GPT init: N(0, initializer_range) on all weight matrices (the paper's
    scheme; the reference test models use Normal(std=0.02) likewise)."""
    return ParamAttr(initializer=Normal(mean=0.0, std=config.initializer_range))


from ._tp import tp_enabled as _tp_enabled  # noqa: E402 (shared TP wiring)


def _linear(config, in_f, out_f, kind):
    """kind: 'col' | 'row' | 'plain' — GPT linears keep their biases."""
    from ._tp import tp_linear

    return tp_linear(config, in_f, out_f, kind, _w(config), has_bias=True)


class GPTEmbeddings(Layer):
    """Token + learned position embeddings with dropout."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        if _tp_enabled(config):
            from ..distributed.fleet.meta_parallel.mp_layers import VocabParallelEmbedding

            self.word_embeddings = VocabParallelEmbedding(
                config.vocab_size, config.hidden_size, weight_attr=_w(config)
            )
        else:
            self.word_embeddings = Embedding(
                config.vocab_size, config.hidden_size, weight_attr=_w(config)
            )
        self.position_embeddings = Embedding(
            config.max_seq_len, config.hidden_size, weight_attr=_w(config)
        )
        self.dropout = Dropout(config.hidden_dropout)

    def forward(self, input_ids, position_ids=None, past_len: int = 0):
        if position_ids is None:
            seq_len = input_ids.shape[-1]
            position_ids = arange(past_len, past_len + seq_len, dtype="int64")
        return self.dropout(
            self.word_embeddings(input_ids)
            + self.position_embeddings(position_ids)
        )


class GPTAttention(Layer):
    """Causal multi-head self-attention (fused qkv projection)."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        h = config.hidden_size
        self.qkv_proj = _linear(config, h, 3 * h, "col")
        self.out_proj = _linear(config, h, h, "row")
        self.attn_dropout = config.attn_dropout
        self.resid_dropout = Dropout(config.hidden_dropout)

    def forward(self, x, attn_mask=None, cache=None):
        cfg = self.config
        b, s = x.shape[0], x.shape[1]
        qkv = self.qkv_proj(x)  # [b, s, 3h]
        qkv = reshape(qkv, [b, s, 3, cfg.num_heads, cfg.head_dim])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]  # [b, s, nh, hd]
        new_cache = None
        past_len = 0
        if cache is not None:
            k_past, v_past = cache
            if k_past is not None:
                past_len = k_past.shape[1]
                k = concat([k_past, k], axis=1)
                v = concat([v_past, v], axis=1)
            new_cache = (k, v)
        # causal handles the cached-prefix case too: _sdpa_ref offsets the
        # tril by (k_len - q_len), i.e. query t attends keys <= past_len + t.
        causal = attn_mask is None and s > 1
        out = F.scaled_dot_product_attention(
            q, k, v,
            attn_mask=attn_mask,
            is_causal=causal,
            dropout_p=self.attn_dropout if self.training else 0.0,
        )  # [b, s, nh, hd]
        out = reshape(out, [b, s, cfg.num_heads * cfg.head_dim])
        out = self.resid_dropout(self.out_proj(out))
        if cache is not None:
            return out, new_cache
        return out


class GPTMLP(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        h, f = config.hidden_size, config.ffn_size
        self.fc1 = _linear(config, h, f, "col")
        self.fc2 = _linear(config, f, h, "row")
        self.dropout = Dropout(config.hidden_dropout)

    def forward(self, x):
        if _fused_mlp_on(self.config):
            from ..incubate.nn import functional as FI

            # bias+gelu ride ONE Pallas epilogue kernel after the GEMM
            y = FI.fused_bias_gelu(
                matmul(x, self.fc1.weight), self.fc1.bias,
                use_pallas=True if self.config.force_fused_mlp else None)
            return self.dropout(self.fc2(y))
        return self.dropout(self.fc2(F.gelu(self.fc1(x), approximate=True)))


def _fused_mlp_on(config: GPTConfig) -> bool:
    # under TP the block runs global-view with mp-sharded weights; GSPMD
    # cannot partition a pallas_call, so the fused path is single-shard only
    return getattr(config, "fused_mlp", False) and not _tp_enabled(config)


class GPTDecoderLayer(Layer):
    """Pre-LN transformer decoder block."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.ln_1 = LayerNorm(config.hidden_size, epsilon=config.layer_norm_eps)
        self.attn = GPTAttention(config)
        self.ln_2 = LayerNorm(config.hidden_size, epsilon=config.layer_norm_eps)
        self.mlp = GPTMLP(config)

    def forward(self, x, attn_mask=None, cache=None):
        if _fused_mlp_on(self.config):
            return self._forward_fused(x, attn_mask=attn_mask, cache=cache)
        if cache is not None:
            a, new_cache = self.attn(self.ln_1(x), attn_mask=attn_mask, cache=cache)
            x = x + a
            x = x + self.mlp(self.ln_2(x))
            return x, new_cache
        x = x + self.attn(self.ln_1(x), attn_mask=attn_mask)
        x = x + self.mlp(self.ln_2(x))
        return x

    def _forward_fused(self, x, attn_mask=None, cache=None):
        """Fused-kernel block: LN1 single-pass, then the attention branch's
        residual add + LN2 in ONE residual-in/residual-out kernel."""
        from ..incubate.nn import functional as FI

        cfg = self.config
        uk = True if cfg.force_fused_mlp else None
        y1 = FI.fused_layer_norm(x, self.ln_1.weight, self.ln_1.bias,
                                 epsilon=cfg.layer_norm_eps, use_pallas=uk)
        new_cache = None
        if cache is not None:
            a, new_cache = self.attn(y1, attn_mask=attn_mask, cache=cache)
        else:
            a = self.attn(y1, attn_mask=attn_mask)
        # s = x + a (residual-out) and y2 = LN(s), one kernel
        y2, s = FI.fused_ln_residual(a, x, self.ln_2.weight, self.ln_2.bias,
                                     epsilon=cfg.layer_norm_eps, use_pallas=uk)
        x = s + self.mlp(y2)
        if cache is not None:
            return x, new_cache
        return x


class GPTModel(Layer):
    """Embeddings + decoder stack + final LN."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.embeddings = GPTEmbeddings(config)
        self.layers = LayerList([GPTDecoderLayer(config) for _ in range(config.num_layers)])
        self.ln_f = LayerNorm(config.hidden_size, epsilon=config.layer_norm_eps)

    def forward(self, input_ids, position_ids=None, attn_mask=None, caches=None):
        past_len = 0
        if caches is not None and caches[0][0] is not None:
            past_len = caches[0][0].shape[1]
        x = self.embeddings(input_ids, position_ids, past_len=past_len)
        new_caches = [] if caches is not None else None
        use_recompute = (getattr(self.config, "recompute", False)
                         and self.training and caches is None)
        if use_recompute:
            from ..distributed.fleet.utils import recompute

        for i, layer in enumerate(self.layers):
            if caches is not None:
                x, c = layer(x, attn_mask=attn_mask, cache=caches[i])
                new_caches.append(c)
            elif use_recompute:
                x = recompute(layer, x, attn_mask=attn_mask)
            else:
                x = layer(x, attn_mask=attn_mask)
        x = self.ln_f(x)
        if caches is not None:
            return x, new_caches
        return x


class GPTPretrainingCriterion(Layer):
    """Shifted next-token cross-entropy (mean over tokens)."""

    def forward(self, logits, labels):
        # logits [b, s, v], labels [b, s]
        loss = F.cross_entropy(
            reshape(logits, [-1, logits.shape[-1]]),
            reshape(labels, [-1]),
            reduction="mean",
        )
        return loss


class GPTForCausalLM(Layer):
    """GPTModel + LM head (weight-tied by default) + optional loss."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.gpt = GPTModel(config)
        if not config.tie_word_embeddings:
            self.lm_head = Linear(
                config.hidden_size, config.vocab_size,
                weight_attr=_w(config), bias_attr=False,
            )
        self.criterion = GPTPretrainingCriterion()

    def _logits(self, hidden):
        if self.config.tie_word_embeddings:
            w = self.gpt.embeddings.word_embeddings.weight  # [v, h]
            return matmul(hidden, w, transpose_y=True)
        return self.lm_head(hidden)

    def forward(self, input_ids, labels=None, position_ids=None, attn_mask=None, caches=None):
        if caches is not None:
            hidden, new_caches = self.gpt(
                input_ids, position_ids=position_ids, attn_mask=attn_mask, caches=caches
            )
            return self._logits(hidden), new_caches
        hidden = self.gpt(input_ids, position_ids=position_ids, attn_mask=attn_mask)
        logits = self._logits(hidden)
        if labels is None:
            return logits
        # standard LM shift: predict token t+1 from prefix ..t
        shift_logits = logits[:, :-1, :]
        shift_labels = labels[:, 1:]
        return self.criterion(shift_logits, shift_labels)
