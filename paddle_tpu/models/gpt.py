"""GPT model family — the flagship benchmark model.

Architecture parity: the reference's fleet GPT test models
(test/collective/fleet/hybrid_parallel_pp_transformer.py,
hybrid_parallel_mp_model.py) and the GPT-3 paper sizes named in BASELINE.md.
Pre-LN decoder blocks, learned positional embeddings, GELU MLP (4x), causal
self-attention through ``F.scaled_dot_product_attention`` (flash-attention
Pallas kernel on TPU when available).

Tensor parallelism: with ``mp_degree > 1`` (or fleet initialised), qkv/out and
mlp projections become Column/RowParallelLinear and the token embedding
VocabParallelEmbedding — the Megatron layout (reference: fleet/layers/mpu/
mp_layers.py:47,:333,:540) where GSPMD emits the collectives.
"""
from __future__ import annotations

import math
from dataclasses import dataclass

from ..framework.param_attr import ParamAttr
from ..nn import Layer, functional as F
from ..nn.initializer import Normal
from ..nn.layer.common import Dropout, Embedding, Linear
from ..nn.layer.container import LayerList
from ..nn.layer.norm import LayerNorm
from ..tensor.creation import arange
from ..tensor.manipulation import concat, reshape
from ..tensor.math import matmul


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    max_seq_len: int = 1024
    intermediate_size: int | None = None  # default 4*hidden
    hidden_dropout: float = 0.0
    attn_dropout: float = 0.0
    layer_norm_eps: float = 1e-5
    initializer_range: float = 0.02
    tie_word_embeddings: bool = True
    use_flash_attention: bool = True
    # run the Pallas kernel in interpret mode off-TPU too (CPU-mesh tests of
    # the sharded kernel path; never set in production configs)
    force_flash: bool = False
    # fused MLP-block Pallas kernels (ops/pallas/fused_mlp): single-pass
    # LN (+ residual-in/out) and bias+gelu epilogues replace the XLA
    # elementwise chains in the decoder block — the round-5 roofline's
    # ~20 ms/step of LN/gelu/residual HBM round-trips. bench.py flips this
    # via --fused-mlp; off by default until the on-chip A/B confirms it.
    fused_mlp: bool = False
    # run the fused MLP kernels in interpret mode off-TPU too (CPU tests)
    force_fused_mlp: bool = False
    # parallel knobs
    tensor_parallel: bool = False  # force TP layers even without fleet
    recompute: bool = False  # rematerialize blocks in backward (activation
    # memory ~O(layers*s*h) instead of O(layers*s*4h stacks))
    remat_save_attn: bool = True  # under recompute, also save the flash
    # kernel's o/lse (backward skips the attention re-forward for
    # ~layers*s*h*2B extra residency); memory-edge configs (1.3B on 16 GB)
    # set False to keep the smaller footprint
    remat_save_ln: bool = False  # under recompute, also save both LN
    # outputs per layer (2*layers*s*h*2B extra residency, ~1.2 GB at 760M
    # bs8): backward skips the LN re-forward (mean/var/normalize passes)
    # perf-attribution ablations (perf_breakdown.py only — differential
    # timing of step phases; never set in training configs): any of
    # {"attn", "mlp", "ce"} ("ce" keeps the lm-head matmul, drops the
    # softmax-CE math)
    ablate: tuple = ()

    @property
    def ffn_size(self) -> int:
        return self.intermediate_size or 4 * self.hidden_size

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    def num_params(self) -> int:
        h, v, l = self.hidden_size, self.vocab_size, self.num_layers
        per_layer = 4 * h * h + 4 * h + 2 * h * self.ffn_size + h + self.ffn_size + 4 * h
        emb = v * h + self.max_seq_len * h
        return emb + l * per_layer + 2 * h


# GPT-3 paper table 2.1 sizes (the BASELINE.md benchmark ladder).
GPT_CONFIGS: dict[str, GPTConfig] = {
    "gpt3-tiny": GPTConfig(vocab_size=1024, hidden_size=128, num_layers=2, num_heads=4, max_seq_len=128),
    "gpt3-125m": GPTConfig(hidden_size=768, num_layers=12, num_heads=12),
    "gpt3-350m": GPTConfig(hidden_size=1024, num_layers=24, num_heads=16),
    "gpt3-760m": GPTConfig(hidden_size=1536, num_layers=24, num_heads=16),
    "gpt3-1.3b": GPTConfig(hidden_size=2048, num_layers=24, num_heads=32, max_seq_len=2048),
    "gpt3-2.7b": GPTConfig(hidden_size=2560, num_layers=32, num_heads=32, max_seq_len=2048),
    "gpt3-6.7b": GPTConfig(hidden_size=4096, num_layers=32, num_heads=32, max_seq_len=2048),
    "gpt3-13b": GPTConfig(hidden_size=5120, num_layers=40, num_heads=40, max_seq_len=2048),
}


def _w(config: GPTConfig) -> ParamAttr:
    """GPT init: N(0, initializer_range) on all weight matrices (the paper's
    scheme; the reference test models use Normal(std=0.02) likewise)."""
    return ParamAttr(initializer=Normal(mean=0.0, std=config.initializer_range))


from ._tp import tp_enabled as _tp_enabled  # noqa: E402 (shared TP wiring)


def _linear(config, in_f, out_f, kind):
    """kind: 'col' | 'row' | 'plain' — GPT linears keep their biases."""
    from ._tp import tp_linear

    return tp_linear(config, in_f, out_f, kind, _w(config), has_bias=True)


class GPTEmbeddings(Layer):
    """Token + learned position embeddings with dropout."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        if _tp_enabled(config):
            from ..distributed.fleet.meta_parallel.mp_layers import VocabParallelEmbedding

            self.word_embeddings = VocabParallelEmbedding(
                config.vocab_size, config.hidden_size, weight_attr=_w(config)
            )
        else:
            self.word_embeddings = Embedding(
                config.vocab_size, config.hidden_size, weight_attr=_w(config)
            )
        self.position_embeddings = Embedding(
            config.max_seq_len, config.hidden_size, weight_attr=_w(config)
        )
        self.dropout = Dropout(config.hidden_dropout)

    def forward(self, input_ids, position_ids=None, past_len: int = 0):
        if position_ids is None:
            seq_len = input_ids.shape[-1]
            position_ids = arange(past_len, past_len + seq_len, dtype="int64")
        return self.dropout(
            self.word_embeddings(input_ids)
            + self.position_embeddings(position_ids)
        )


class GPTAttention(Layer):
    """Causal multi-head self-attention (fused qkv projection)."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        h = config.hidden_size
        self.qkv_proj = _linear(config, h, 3 * h, "col")
        self.out_proj = _linear(config, h, h, "row")
        self.attn_dropout = config.attn_dropout
        self.resid_dropout = Dropout(config.hidden_dropout)

    def forward(self, x, attn_mask=None, cache=None):
        cfg = self.config
        b, s = x.shape[0], x.shape[1]
        qkv = self.qkv_proj(x)  # [b, s, 3h]
        qkv = reshape(qkv, [b, s, 3, cfg.num_heads, cfg.head_dim])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]  # [b, s, nh, hd]
        new_cache = None
        past_len = 0
        if cache is not None:
            k_past, v_past = cache
            if k_past is not None:
                past_len = k_past.shape[1]
                k = concat([k_past, k], axis=1)
                v = concat([v_past, v], axis=1)
            new_cache = (k, v)
        # causal handles the cached-prefix case too: _sdpa_ref offsets the
        # tril by (k_len - q_len), i.e. query t attends keys <= past_len + t.
        causal = attn_mask is None and s > 1
        out = F.scaled_dot_product_attention(
            q, k, v,
            attn_mask=attn_mask,
            is_causal=causal,
            dropout_p=self.attn_dropout if self.training else 0.0,
        )  # [b, s, nh, hd]
        out = reshape(out, [b, s, cfg.num_heads * cfg.head_dim])
        out = self.resid_dropout(self.out_proj(out))
        if cache is not None:
            return out, new_cache
        return out


class GPTMLP(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        h, f = config.hidden_size, config.ffn_size
        self.fc1 = _linear(config, h, f, "col")
        self.fc2 = _linear(config, f, h, "row")
        self.dropout = Dropout(config.hidden_dropout)

    def forward(self, x):
        if _fused_mlp_on(self.config):
            from ..incubate.nn import functional as FI

            # bias+gelu ride ONE Pallas epilogue kernel after the GEMM
            y = FI.fused_bias_gelu(
                matmul(x, self.fc1.weight), self.fc1.bias,
                use_pallas=True if self.config.force_fused_mlp else None)
            return self.dropout(self.fc2(y))
        return self.dropout(self.fc2(F.gelu(self.fc1(x), approximate=True)))


def _fused_mlp_on(config: GPTConfig) -> bool:
    # under TP the block runs global-view with mp-sharded weights; GSPMD
    # cannot partition a pallas_call, so the fused path is single-shard only
    return getattr(config, "fused_mlp", False) and not _tp_enabled(config)


class GPTDecoderLayer(Layer):
    """Pre-LN transformer decoder block."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.ln_1 = LayerNorm(config.hidden_size, epsilon=config.layer_norm_eps)
        self.attn = GPTAttention(config)
        self.ln_2 = LayerNorm(config.hidden_size, epsilon=config.layer_norm_eps)
        self.mlp = GPTMLP(config)

    def forward(self, x, attn_mask=None, cache=None):
        if _fused_mlp_on(self.config):
            return self._forward_fused(x, attn_mask=attn_mask, cache=cache)
        if cache is not None:
            a, new_cache = self.attn(self.ln_1(x), attn_mask=attn_mask, cache=cache)
            x = x + a
            x = x + self.mlp(self.ln_2(x))
            return x, new_cache
        x = x + self.attn(self.ln_1(x), attn_mask=attn_mask)
        x = x + self.mlp(self.ln_2(x))
        return x

    def _forward_fused(self, x, attn_mask=None, cache=None):
        """Fused-kernel block: LN1 single-pass, then the attention branch's
        residual add + LN2 in ONE residual-in/residual-out kernel."""
        from ..incubate.nn import functional as FI

        cfg = self.config
        uk = True if cfg.force_fused_mlp else None
        y1 = FI.fused_layer_norm(x, self.ln_1.weight, self.ln_1.bias,
                                 epsilon=cfg.layer_norm_eps, use_pallas=uk)
        new_cache = None
        if cache is not None:
            a, new_cache = self.attn(y1, attn_mask=attn_mask, cache=cache)
        else:
            a = self.attn(y1, attn_mask=attn_mask)
        # s = x + a (residual-out) and y2 = LN(s), one kernel
        y2, s = FI.fused_ln_residual(a, x, self.ln_2.weight, self.ln_2.bias,
                                     epsilon=cfg.layer_norm_eps, use_pallas=uk)
        x = s + self.mlp(y2)
        if cache is not None:
            return x, new_cache
        return x


class GPTModel(Layer):
    """Embeddings + decoder stack + final LN."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.embeddings = GPTEmbeddings(config)
        self.layers = LayerList([GPTDecoderLayer(config) for _ in range(config.num_layers)])
        self.ln_f = LayerNorm(config.hidden_size, epsilon=config.layer_norm_eps)

    def forward(self, input_ids, position_ids=None, attn_mask=None, caches=None):
        past_len = 0
        if caches is not None and caches[0][0] is not None:
            past_len = caches[0][0].shape[1]
        x = self.embeddings(input_ids, position_ids, past_len=past_len)
        new_caches = [] if caches is not None else None
        use_recompute = (getattr(self.config, "recompute", False)
                         and self.training and caches is None)
        if use_recompute:
            from ..distributed.fleet.utils import recompute

        for i, layer in enumerate(self.layers):
            if caches is not None:
                x, c = layer(x, attn_mask=attn_mask, cache=caches[i])
                new_caches.append(c)
            elif use_recompute:
                x = recompute(layer, x, attn_mask=attn_mask)
            else:
                x = layer(x, attn_mask=attn_mask)
        x = self.ln_f(x)
        if caches is not None:
            return x, new_caches
        return x

    def generate(self, input_ids, max_new_tokens=20, **kw):
        """Greedy decoding over the paged KV cache with the tied-embedding
        LM head — see :func:`generate_paged`."""
        return generate_paged(self, input_ids, max_new_tokens, **kw)


class GPTPretrainingCriterion(Layer):
    """Shifted next-token cross-entropy (mean over tokens)."""

    def forward(self, logits, labels):
        # logits [b, s, v], labels [b, s]
        loss = F.cross_entropy(
            reshape(logits, [-1, logits.shape[-1]]),
            reshape(labels, [-1]),
            reduction="mean",
        )
        return loss


class GPTForCausalLM(Layer):
    """GPTModel + LM head (weight-tied by default) + optional loss."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.gpt = GPTModel(config)
        if not config.tie_word_embeddings:
            self.lm_head = Linear(
                config.hidden_size, config.vocab_size,
                weight_attr=_w(config), bias_attr=False,
            )
        self.criterion = GPTPretrainingCriterion()

    def _logits(self, hidden):
        if self.config.tie_word_embeddings:
            w = self.gpt.embeddings.word_embeddings.weight  # [v, h]
            return matmul(hidden, w, transpose_y=True)
        return self.lm_head(hidden)

    def forward(self, input_ids, labels=None, position_ids=None, attn_mask=None, caches=None):
        if caches is not None:
            hidden, new_caches = self.gpt(
                input_ids, position_ids=position_ids, attn_mask=attn_mask, caches=caches
            )
            return self._logits(hidden), new_caches
        hidden = self.gpt(input_ids, position_ids=position_ids, attn_mask=attn_mask)
        logits = self._logits(hidden)
        if labels is None:
            return logits
        # standard LM shift: predict token t+1 from prefix ..t
        shift_logits = logits[:, :-1, :]
        shift_labels = labels[:, 1:]
        return self.criterion(shift_logits, shift_labels)

    def generate(self, input_ids, max_new_tokens=20, **kw):
        """Greedy autoregressive decoding over the paged KV cache — see
        :func:`generate_paged`."""
        return generate_paged(self, input_ids, max_new_tokens, **kw)


# ---------------------------------------------------------------------------
# Round-7 serving path: paged KV cache + fixed-shape decode step.
#
# The autoregressive analog of gpt_spmd's training step: pure functions over
# a params pytree EXTRACTED from the Layer model (one-time, zero-copy on the
# underlying arrays), so prefill compiles as ONE jit and every decode step
# replays ONE fixed-shape jit — no per-token Python dispatch, no retrace
# (MPK's whole-step-as-one-program argument, arxiv 2512.22219). K/V live in
# the paged pool managed by inference.kv_cache.KVCacheManager and attention
# over the ragged batch runs the Pallas paged decode kernel
# (ops/pallas/paged_attention, arxiv 2604.15464).
# ---------------------------------------------------------------------------


# the ONE per-layer weight table: serving_params' stacks AND the params
# cache's staleness walk both derive from it, so adding a per-layer weight
# cannot desync the cache oracle from the extraction
_SRV_LAYER_WEIGHTS = (
    ("ln1_g", lambda l: l.ln_1.weight), ("ln1_b", lambda l: l.ln_1.bias),
    ("wqkv", lambda l: l.attn.qkv_proj.weight),
    ("bqkv", lambda l: l.attn.qkv_proj.bias),
    ("wo", lambda l: l.attn.out_proj.weight),
    ("bo", lambda l: l.attn.out_proj.bias),
    ("ln2_g", lambda l: l.ln_2.weight), ("ln2_b", lambda l: l.ln_2.bias),
    ("w1", lambda l: l.mlp.fc1.weight), ("b1", lambda l: l.mlp.fc1.bias),
    ("w2", lambda l: l.mlp.fc2.weight), ("b2", lambda l: l.mlp.fc2.bias),
)


def _srv_nonlayer_weights(model):
    gpt = model.gpt if hasattr(model, "gpt") else model
    ws = [("tok_emb", gpt.embeddings.word_embeddings.weight),
          ("pos_emb", gpt.embeddings.position_embeddings.weight),
          ("lnf_g", gpt.ln_f.weight), ("lnf_b", gpt.ln_f.bias)]
    if getattr(model, "lm_head", None) is not None:
        ws.append(("lm_head", model.lm_head.weight))
    return ws


def _serving_weight_buffers(model):
    """The model's live weight buffers — buffer identity is the staleness
    key for the per-model params cache (an optimizer step rebinds
    ``._data``, so stale ids mean re-extract)."""
    gpt = model.gpt if hasattr(model, "gpt") else model
    bufs = [t._data for _, t in _srv_nonlayer_weights(model)]
    for l in gpt.layers:
        bufs += [get(l)._data for _, get in _SRV_LAYER_WEIGHTS]
    return bufs


def serving_params(model):
    """Extract the serving params pytree from a GPTForCausalLM / GPTModel.

    Per-layer weights stack on a leading [L, ...] dim so the blocks run
    under ``lax.scan`` (one compiled block, not L unrolled copies). The
    stacks are device COPIES (~1x extra weight memory while they live);
    the embeddings / final-LN / lm-head leaves are views of the live
    buffers. ``generate_paged`` caches the extraction per model (see
    :func:`_serving_params_cached`) so repeated calls don't re-stack.
    """
    import jax.numpy as jnp

    gpt = model.gpt if hasattr(model, "gpt") else model
    cfg = gpt.config
    if _tp_enabled(cfg):
        raise NotImplementedError(
            "the paged serving path is single-shard (GSPMD cannot partition "
            "the pallas decode kernel); run without tensor parallelism")

    params = {k: t._data for k, t in _srv_nonlayer_weights(model)}
    params["layers"] = {
        k: jnp.stack([get(l)._data for l in gpt.layers])
        for k, get in _SRV_LAYER_WEIGHTS
    }
    return params  # lm_head (when untied) rides _srv_nonlayer_weights


# NOTE: _srv_ln/_srv_mlp/the prefill block are the serving-side pure
# spellings of the decoder block — keep their math in lockstep with the
# eager Layer classes above AND gpt_spmd's _layer_norm/_block_mlp (same
# params-dict key schema); a drift in eps/gelu/LN-stat handling makes
# generate() disagree with the trained model. The fp32 LN statistics here
# are intentional (decode runs the weights' dtype, stats stay fp32).
def _srv_ln(x, g, b, eps):
    import jax

    import jax.numpy as jnp

    xf = x.astype(jnp.float32)
    mu = xf.mean(-1, keepdims=True)
    var = xf.var(-1, keepdims=True)
    return (((xf - mu) * jax.lax.rsqrt(var + eps)) * g + b).astype(x.dtype)


def _srv_logits(params, h):
    """h [..., hidden] -> logits [..., vocab] (tied head unless lm_head)."""
    import jax.numpy as jnp

    if "lm_head" in params:
        return h @ params["lm_head"]
    return jnp.einsum("...h,vh->...v", h, params["tok_emb"])


def _srv_mlp(p, y):
    import jax

    return (jax.nn.gelu(y @ p["w1"] + p["b1"], approximate=True)
            @ p["w2"] + p["b2"])


def build_prefill(config: GPTConfig, page_size: int):
    """One-jit prefill: forward the (right-padded) prompts, scatter each
    slot's K/V into its pages, return the next-token ids + logits at each
    prompt's last valid position.

    Signature: ``fn(params, ids[b,s], lengths[b], k_pages, v_pages,
    pages[b,pps]) -> (next_ids[b], logits[b,v], k_pages, v_pages)``.
    Ragged prompts ride right-padding: causal masking keeps padded columns
    out of every valid row's softmax, and the page scatter drops positions
    past each length.
    """
    import jax
    import jax.numpy as jnp

    from ..inference.kv_cache import paged_write_prefill

    cfg = config
    eps = cfg.layer_norm_eps

    def prefill(params, ids, lengths, k_pages, v_pages, pages):
        # MXU-native matmul precision (gpt_spmd.loss_fn convention): the
        # framework-global "highest" would emulate bf16 serving matmuls
        # multi-pass, 3-6x slower; attention scores stay explicit fp32
        with jax.default_matmul_precision("default"):
            return _prefill_inner(params, ids, lengths, k_pages, v_pages,
                                  pages)

    def _prefill_inner(params, ids, lengths, k_pages, v_pages, pages):
        b, s = ids.shape
        nh, hd = cfg.num_heads, cfg.head_dim
        x = (jnp.take(params["tok_emb"], ids, axis=0)
             + params["pos_emb"][:s])

        def block(x, p):
            y = _srv_ln(x, p["ln1_g"], p["ln1_b"], eps)
            qkv = (y @ p["wqkv"] + p["bqkv"]).reshape(b, s, 3, nh, hd)
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
            s_ = jnp.einsum("bqnd,bknd->bnqk", q.astype(jnp.float32),
                            k.astype(jnp.float32)) / math.sqrt(hd)
            causal = jnp.tril(jnp.ones((s, s), bool))
            s_ = jnp.where(causal[None, None], s_, -1e30)
            a = jnp.einsum("bnqk,bknd->bqnd",
                           jax.nn.softmax(s_, axis=-1),
                           v.astype(jnp.float32)).astype(x.dtype)
            x = x + a.reshape(b, s, nh * hd) @ p["wo"] + p["bo"]
            x = x + _srv_mlp(p, _srv_ln(x, p["ln2_g"], p["ln2_b"], eps))
            return x, (k, v)

        x, (ks, vs) = jax.lax.scan(block, x, params["layers"])
        x = _srv_ln(x, params["lnf_g"], params["lnf_b"], eps)
        h_last = x[jnp.arange(b), jnp.maximum(lengths - 1, 0)]
        logits = _srv_logits(params, h_last).astype(jnp.float32)
        next_ids = jnp.argmax(logits, axis=-1).astype(jnp.int32)

        # copy-on-prefill: scatter every slot's K/V into its pages.
        # ks: [L, b, s, nh, hd] -> per (layer, slot) writes, vmapped over L
        def write_all(pool, seqs):
            for bi in range(b):  # b is static; unrolls into b scatters
                pool = jax.vmap(
                    paged_write_prefill, in_axes=(0, 0, None, None, None)
                )(pool, seqs[:, bi], pages[bi], lengths[bi], page_size)
            return pool

        k_pages = write_all(k_pages, ks)
        v_pages = write_all(v_pages, vs)
        return next_ids, logits, k_pages, v_pages

    # donate the pools like the decode step: every admission threads the
    # full cache through this jit, and an un-donated scatter would copy it
    return jax.jit(prefill, donate_argnums=(3, 4))


def build_decode_step(config: GPTConfig, page_size: int,
                      use_kernel: bool | None = None):
    """The fixed-shape decode step, compiled once per (batch, cache
    geometry): embed the incoming token, write its K/V into the pages,
    paged-attend over every layer, emit the greedy next token.

    Signature: ``fn(params, ids[b], lengths[b], k_pages, v_pages,
    page_table[b,pps]) -> (next_ids[b], logits[b,v], k_pages, v_pages)``.
    ``lengths`` counts tokens already cached per slot (0 = empty slot —
    its lane computes masked garbage and writes nothing). Every array
    argument keeps its shape step over step, so after the first call the
    loop replays one compiled program — ``fn.trace_count[0]`` exposes the
    trace count for the no-retrace gate.
    """
    import jax
    import jax.numpy as jnp

    from ..inference.kv_cache import paged_write_tokens
    from ..ops.pallas.paged_attention import paged_attention

    cfg = config
    eps = cfg.layer_norm_eps
    trace_count = [0]

    def step(params, ids, lengths, k_pages, v_pages, page_table):
        # MXU-native matmul precision — see build_prefill
        with jax.default_matmul_precision("default"):
            return _step_inner(params, ids, lengths, k_pages, v_pages,
                               page_table)

    def _step_inner(params, ids, lengths, k_pages, v_pages, page_table):
        trace_count[0] += 1
        b = ids.shape[0]
        nh, hd = cfg.num_heads, cfg.head_dim
        active = lengths > 0
        pos = jnp.where(active, lengths, -1)  # write position = current len
        pos_emb_idx = jnp.clip(jnp.maximum(lengths, 0),
                               0, params["pos_emb"].shape[0] - 1)
        x = (jnp.take(params["tok_emb"], jnp.maximum(ids, 0), axis=0)
             + params["pos_emb"][pos_emb_idx])          # [b, h]
        ctx = jnp.where(active, lengths + 1, 0).astype(jnp.int32)

        def block(x, layer):
            p, kp, vp = layer
            y = _srv_ln(x, p["ln1_g"], p["ln1_b"], eps)
            qkv = (y @ p["wqkv"] + p["bqkv"]).reshape(b, 3, nh, hd)
            q, k_tok, v_tok = qkv[:, 0], qkv[:, 1], qkv[:, 2]
            kp = paged_write_tokens(kp, k_tok, page_table, pos, page_size)
            vp = paged_write_tokens(vp, v_tok, page_table, pos, page_size)
            a = paged_attention(q, kp, vp, page_table, ctx,
                                use_kernel=use_kernel)  # [b, nh, hd]
            x = x + a.reshape(b, nh * hd) @ p["wo"] + p["bo"]
            x = x + _srv_mlp(p, _srv_ln(x, p["ln2_g"], p["ln2_b"], eps))
            return x, (kp, vp)

        x, (k_pages, v_pages) = jax.lax.scan(
            block, x, (params["layers"], k_pages, v_pages))
        x = _srv_ln(x, params["lnf_g"], params["lnf_b"], eps)
        logits = _srv_logits(params, x).astype(jnp.float32)
        next_ids = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return next_ids, logits, k_pages, v_pages

    # donate the page pools: the step rewrites them, and double-buffering
    # the cache (the biggest serving allocation) would halve capacity
    jitted = jax.jit(step, donate_argnums=(3, 4))
    jitted.trace_count = trace_count
    return jitted


# generate_paged's compiled programs, keyed by (config fields, page_size,
# use_kernel): repeated generate() calls replay the same jit instead of
# re-tracing + re-compiling the whole model each call. ServingPredictor
# holds its own per-instance pair (its trace counter is a per-predictor
# gate), so only the convenience path shares.
_SERVING_JIT_CACHE: dict = {}

# per-model extracted params (the [L, ...] stacks are device copies):
# weak-keyed so a collected model drops its stacks, id-validated so an
# optimizer step (which rebinds every ._data) forces re-extraction
import weakref as _weakref  # noqa: E402

_SERVING_PARAMS_CACHE = _weakref.WeakKeyDictionary()


def _serving_params_cached(model):
    # staleness check by buffer IDENTITY against WEAKLY-held capture-time
    # buffers: identity comparison is immune to CPython id reuse, and the
    # weakrefs mean an optimizer step's rebinding doesn't leave ~1x model
    # weights of dead buffers pinned by the cache key (a dead ref simply
    # reads as stale)
    bufs = _serving_weight_buffers(model)
    hit = _SERVING_PARAMS_CACHE.get(model)
    if (hit is not None and len(hit[0]) == len(bufs)
            and all(ref() is cur for ref, cur in zip(hit[0], bufs))):
        return hit[1]
    params = serving_params(model)
    try:
        _SERVING_PARAMS_CACHE[model] = (
            [_weakref.ref(b) for b in bufs], params)
    except TypeError:
        pass  # un-weakrefable model object: just skip the cache
    return params


def _serving_fns(config: GPTConfig, page_size: int, use_kernel):
    import dataclasses

    key = (tuple((f.name, getattr(config, f.name))
                 for f in dataclasses.fields(config)),
           page_size, use_kernel)
    hit = _SERVING_JIT_CACHE.get(key)
    if hit is None:
        # bounded LRU (same policy as the engine's eager-op cache): a
        # process sweeping geometries must not pin executables forever
        while len(_SERVING_JIT_CACHE) >= 32:
            _SERVING_JIT_CACHE.pop(next(iter(_SERVING_JIT_CACHE)))
        hit = (build_prefill(config, page_size),
               build_decode_step(config, page_size, use_kernel=use_kernel))
    else:
        _SERVING_JIT_CACHE.pop(key)  # refresh recency
    _SERVING_JIT_CACHE[key] = hit
    return hit


def generate_paged(model, input_ids, max_new_tokens=20, *, page_size=None,
                   num_pages=None, use_kernel=None, eos_token_id=None):
    """Greedy autoregressive generation over the paged KV cache.

    ``input_ids``: [batch, prompt_len] (Tensor or array). Returns an int64
    Tensor [batch, <= max_new_tokens] of generated ids (prefill as one jit,
    then one fixed-shape decode jit per token — no retrace after warmup).
    With ``eos_token_id``, a row that stops early frees its cache pages,
    its lane goes inert, and its remaining columns pad with the eos id.
    """
    import numpy as np

    import jax.numpy as jnp

    from ..inference.kv_cache import KVCacheManager, pages_needed
    from ..tensor.tensor import Tensor

    cfg = (model.gpt if hasattr(model, "gpt") else model).config
    ids_np = np.asarray(input_ids.numpy() if isinstance(input_ids, Tensor)
                        else input_ids).astype(np.int32)
    b, s = ids_np.shape
    if s == 0:
        raise ValueError("empty prompt")
    if max_new_tokens <= 0:
        generate_paged.last_decode_trace_count = 0
        return Tensor(jnp.zeros((b, 0), jnp.int64))
    total = s + max_new_tokens
    if total > cfg.max_seq_len:
        raise ValueError(
            f"prompt {s} + max_new_tokens {max_new_tokens} exceeds "
            f"max_seq_len {cfg.max_seq_len}")
    params = _serving_params_cached(model)
    dtype = params["tok_emb"].dtype
    if page_size is None:
        from ..ops.pallas.paged_attention import preferred_page_size

        page_size = preferred_page_size(cfg.num_heads, cfg.num_heads,
                                        cfg.head_dim, dtype)
    mgr = KVCacheManager(
        cfg.num_layers, cfg.num_heads, cfg.head_dim,
        num_pages=num_pages or b * pages_needed(total, page_size),
        max_batch=b, max_seq_len=total, page_size=page_size, dtype=dtype)
    slots = [mgr.admit(s) for _ in range(b)]

    prefill, decode = _serving_fns(cfg, mgr.page_size, use_kernel)
    traces_at_entry = decode.trace_count[0]
    next_ids, _, kp, vp = prefill(
        params, jnp.asarray(ids_np), jnp.full((b,), s, jnp.int32),
        mgr.k_pages, mgr.v_pages,
        jnp.stack([mgr.slot_pages(sl) for sl in slots]))
    mgr.update_pages(kp, vp)

    out = [np.asarray(next_ids)]
    done = np.zeros((b,), bool)
    if eos_token_id is not None:
        done |= out[0] == eos_token_id
    cur = next_ids
    for _ in range(max_new_tokens - 1):
        if done.all():
            break
        # free ALL eos lanes first (seq_len 0 parks the decode lane — no
        # writes, zero attention), THEN grow the live ones: a tight pool
        # must see the reclaimed pages before any capacity check can fail
        for i, sl in enumerate(slots):
            if done[i] and sl is not None:
                mgr.free(sl)
                slots[i] = None
        for i, sl in enumerate(slots):
            if done[i]:
                continue
            if not mgr.ensure_capacity(sl, mgr.seq_len(sl) + 1):
                # an undersized pool must fail loudly: the dropped K/V
                # write would otherwise silently corrupt every later token
                raise RuntimeError(
                    f"KV cache exhausted growing slot {sl} to "
                    f"{mgr.seq_len(sl) + 1} tokens — pass a larger "
                    "num_pages (or use ServingPredictor, which preempts)")
        cur, _, kp, vp = decode(
            params, cur, mgr.seq_lens_device(), mgr.k_pages, mgr.v_pages,
            mgr.page_table_device())
        mgr.update_pages(kp, vp)
        for i, sl in enumerate(slots):
            if sl is not None and not done[i]:
                mgr.advance(sl)
        tok = np.asarray(cur)
        if eos_token_id is not None:
            # finished rows pad with eos (their inert lane's argmax is
            # meaningless)
            tok = np.where(done, eos_token_id, tok).astype(tok.dtype)
        out.append(tok)
        if eos_token_id is not None:
            done |= tok == eos_token_id
    # traces THIS call added: 1 on a cold shape, 0 when the cached jit
    # already compiled it — never per-token (the no-retrace gate)
    generate_paged.last_decode_trace_count = (decode.trace_count[0]
                                              - traces_at_entry)
    return Tensor(jnp.asarray(np.stack(out, axis=1), jnp.int64))
