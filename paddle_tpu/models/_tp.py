"""Shared Megatron-style TP wiring for the model families (llama/ernie).

One home for the ambient-mp detection and the col/row/plain linear choice
(reference mp_layers.py:47/:333/:540) so TP behavior changes apply to every
model family at once.
"""
from __future__ import annotations


def mp_degree() -> int:
    """Ambient model-parallel degree from the fleet HCG (0 when absent)."""
    from ..distributed.fleet.meta_parallel import _get_hcg

    hcg = _get_hcg()
    return hcg.get_model_parallel_world_size() if hcg is not None else 0


def tp_enabled(config) -> bool:
    """TP is on when the config forces it or an mp>1 fleet mesh is live."""
    return bool(getattr(config, "tensor_parallel", False)) or mp_degree() > 1


def tp_linear(config, in_f, out_f, kind, weight_attr, has_bias):
    """kind: 'col' (shard output dim) | 'row' (shard input dim) | 'plain'."""
    from ..nn.layer.common import Linear

    if tp_enabled(config) and kind != "plain":
        from ..distributed.fleet.meta_parallel.mp_layers import (
            ColumnParallelLinear,
            RowParallelLinear,
        )

        if kind == "col":
            return ColumnParallelLinear(in_f, out_f, weight_attr=weight_attr,
                                        has_bias=has_bias,
                                        gather_output=False)
        return RowParallelLinear(in_f, out_f, weight_attr=weight_attr,
                                 has_bias=has_bias, input_is_parallel=True)
    return Linear(in_f, out_f, weight_attr=weight_attr,
                  bias_attr=None if has_bias else False)
