"""paddle_tpu.models — flagship model families.

The reference ships its model zoo outside the framework repo (PaddleNLP /
PaddleClas); the in-repo parity points are the fleet hybrid-parallel test
models (test/collective/fleet/hybrid_parallel_*_model.py) and test/book.
These built-in families are the benchmark/flagship configurations named in
BASELINE.md (GPT-3 sizes, ResNet for config 1, BERT for config 2).
"""
from .bert import (
    BERT_CONFIGS,
    BertConfig,
    BertForPretraining,
    BertForSequenceClassification,
    BertModel,
)
from .ernie import (
    ERNIE_CONFIGS,
    ErnieConfig,
    ErnieForPretraining,
    ErnieForSequenceClassification,
    ErnieModel,
)
from .llama import (
    LLAMA_CONFIGS,
    LlamaConfig,
    LlamaForCausalLM,
    LlamaModel,
)
from .gpt import (
    GPT_CONFIGS,
    GPTConfig,
    GPTDecoderLayer,
    GPTEmbeddings,
    GPTForCausalLM,
    GPTModel,
    GPTPretrainingCriterion,
)

__all__ = [
    "BERT_CONFIGS",
    "BertConfig",
    "BertForPretraining",
    "BertForSequenceClassification",
    "BertModel",
    "GPT_CONFIGS",
    "GPTConfig",
    "GPTDecoderLayer",
    "GPTEmbeddings",
    "GPTForCausalLM",
    "GPTModel",
    "GPTPretrainingCriterion",
]
