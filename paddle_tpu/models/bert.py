"""BERT model family — BASELINE config 2 (BERT-base pretraining via jit).

Architecture parity: the reference's transformer encoder surface
(python/paddle/nn/layer/transformer.py TransformerEncoder) as configured by
the standard bert-base/large checkpoints; pretraining heads = MLM + NSP.
"""
from __future__ import annotations

from dataclasses import dataclass

from ..framework.param_attr import ParamAttr
from ..nn import Layer, functional as F
from ..nn.initializer import Normal
from ..nn.layer.common import Dropout, Embedding, Linear
from ..nn.layer.container import LayerList
from ..nn.layer.norm import LayerNorm
from ..tensor.creation import arange, zeros
from ..tensor.manipulation import reshape
from ..tensor.math import matmul, tanh


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_dropout: float = 0.1
    attn_dropout: float = 0.1
    layer_norm_eps: float = 1e-12
    initializer_range: float = 0.02

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


BERT_CONFIGS = {
    "bert-base": BertConfig(),
    "bert-large": BertConfig(hidden_size=1024, num_layers=24, num_heads=16, intermediate_size=4096),
    "bert-tiny": BertConfig(vocab_size=1024, hidden_size=128, num_layers=2, num_heads=2, intermediate_size=512, max_position_embeddings=128),
}


def _w(config):
    return ParamAttr(initializer=Normal(mean=0.0, std=config.initializer_range))


class BertEmbeddings(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.word_embeddings = Embedding(config.vocab_size, config.hidden_size, weight_attr=_w(config))
        self.position_embeddings = Embedding(config.max_position_embeddings, config.hidden_size, weight_attr=_w(config))
        self.token_type_embeddings = Embedding(config.type_vocab_size, config.hidden_size, weight_attr=_w(config))
        self.layer_norm = LayerNorm(config.hidden_size, epsilon=config.layer_norm_eps)
        self.dropout = Dropout(config.hidden_dropout)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        s = input_ids.shape[-1]
        if position_ids is None:
            position_ids = arange(0, s, dtype="int64")
        if token_type_ids is None:
            token_type_ids = zeros(list(input_ids.shape), dtype="int64")
        x = (
            self.word_embeddings(input_ids)
            + self.position_embeddings(position_ids)
            + self.token_type_embeddings(token_type_ids)
        )
        return self.dropout(self.layer_norm(x))


class BertSelfAttention(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        h = config.hidden_size
        self.config = config
        self.qkv = Linear(h, 3 * h, weight_attr=_w(config))
        self.out = Linear(h, h, weight_attr=_w(config))
        self.dropout = Dropout(config.hidden_dropout)
        self.attn_dropout = config.attn_dropout

    def forward(self, x, attn_mask=None):
        cfg = self.config
        b, s = x.shape[0], x.shape[1]
        qkv = reshape(self.qkv(x), [b, s, 3, cfg.num_heads, cfg.head_dim])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        o = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, is_causal=False,
            dropout_p=self.attn_dropout if self.training else 0.0,
        )
        o = reshape(o, [b, s, cfg.num_heads * cfg.head_dim])
        return self.dropout(self.out(o))


class BertLayer(Layer):
    """Post-LN encoder block (original BERT)."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.attention = BertSelfAttention(config)
        self.ln1 = LayerNorm(config.hidden_size, epsilon=config.layer_norm_eps)
        self.fc1 = Linear(config.hidden_size, config.intermediate_size, weight_attr=_w(config))
        self.fc2 = Linear(config.intermediate_size, config.hidden_size, weight_attr=_w(config))
        self.ln2 = LayerNorm(config.hidden_size, epsilon=config.layer_norm_eps)
        self.dropout = Dropout(config.hidden_dropout)

    def forward(self, x, attn_mask=None):
        x = self.ln1(x + self.attention(x, attn_mask))
        y = self.dropout(self.fc2(F.gelu(self.fc1(x), approximate=False)))
        return self.ln2(x + y)


class BertPooler(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.dense = Linear(config.hidden_size, config.hidden_size, weight_attr=_w(config))

    def forward(self, hidden):
        return tanh(self.dense(hidden[:, 0]))


class BertModel(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.embeddings = BertEmbeddings(config)
        self.encoder = LayerList([BertLayer(config) for _ in range(config.num_layers)])
        self.pooler = BertPooler(config)

    def forward(self, input_ids, token_type_ids=None, position_ids=None, attention_mask=None):
        if attention_mask is not None:
            # [b, s] 1/0 -> additive [b, 1, 1, s]
            m = (1.0 - attention_mask.astype("float32")) * -1e9
            attention_mask = reshape(m, [m.shape[0], 1, 1, m.shape[-1]])
        x = self.embeddings(input_ids, token_type_ids, position_ids)
        for layer in self.encoder:
            x = layer(x, attention_mask)
        return x, self.pooler(x)


class BertPretrainingHeads(Layer):
    def __init__(self, config: BertConfig, embedding_weights=None):
        super().__init__()
        self.transform = Linear(config.hidden_size, config.hidden_size, weight_attr=_w(config))
        self.layer_norm = LayerNorm(config.hidden_size, epsilon=config.layer_norm_eps)
        self._tied = embedding_weights  # [vocab, hidden]
        self.decoder_bias = self.create_parameter(
            [config.vocab_size], is_bias=True
        )
        self.seq_relationship = Linear(config.hidden_size, 2, weight_attr=_w(config))

    def forward(self, sequence_output, pooled_output):
        x = self.layer_norm(F.gelu(self.transform(sequence_output), approximate=False))
        mlm_logits = matmul(x, self._tied, transpose_y=True) + self.decoder_bias
        nsp_logits = self.seq_relationship(pooled_output)
        return mlm_logits, nsp_logits


class BertForPretraining(Layer):
    """MLM + NSP pretraining objective."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.bert = BertModel(config)
        self.cls = BertPretrainingHeads(
            config, embedding_weights=self.bert.embeddings.word_embeddings.weight
        )

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                masked_lm_labels=None, next_sentence_label=None):
        seq, pooled = self.bert(input_ids, token_type_ids, attention_mask=attention_mask)
        mlm_logits, nsp_logits = self.cls(seq, pooled)
        if masked_lm_labels is None:
            return mlm_logits, nsp_logits
        mlm_loss = F.cross_entropy(
            reshape(mlm_logits, [-1, self.config.vocab_size]),
            reshape(masked_lm_labels, [-1]),
            ignore_index=-100,
            reduction="mean",
        )
        loss = mlm_loss
        if next_sentence_label is not None:
            loss = loss + F.cross_entropy(
                nsp_logits, reshape(next_sentence_label, [-1]), reduction="mean"
            )
        return loss


class BertForSequenceClassification(Layer):
    def __init__(self, config: BertConfig, num_classes: int = 2):
        super().__init__()
        self.bert = BertModel(config)
        self.dropout = Dropout(config.hidden_dropout)
        self.classifier = Linear(config.hidden_size, num_classes, weight_attr=_w(config))

    def forward(self, input_ids, token_type_ids=None, attention_mask=None, labels=None):
        _, pooled = self.bert(input_ids, token_type_ids, attention_mask=attention_mask)
        logits = self.classifier(self.dropout(pooled))
        if labels is None:
            return logits
        return F.cross_entropy(logits, labels, reduction="mean")
