"""GPT SPMD training step: dp × pp × mp (+sequence-parallel) over one mesh.

This is the compiled hybrid-parallel path — the TPU-native equivalent of the
reference's fleet hybrid engine (SURVEY.md §3.3: CommunicateTopology +
PipelineParallel 1F1B + Megatron TP + sequence parallel), expressed the XLA
way:

- **dp**: batch dim sharded over ``dp``; gradient all-reduce emitted by GSPMD
  (params replicated over dp).
- **mp (TP)**: Megatron column/row sharding on qkv/mlp weights + vocab-sharded
  embedding (reference mp_layers.py:47,:333,:540); collectives emitted by
  GSPMD from the weight shardings + activation constraints.
- **sp**: between attention/mlp regions activations are sharded over ``mp`` on
  the *sequence* dim (reference sequence_parallel_utils.py) via sharding
  constraints — GSPMD turns the row-linear all-reduce into
  reduce-scatter + all-gather exactly like the reference's SP layers.
- **pp**: stacked-stage GSPMD pipelining (stage weights stacked on a leading
  dim sharded over ``pp``): all stages compute in parallel under ``vmap``
  over the stacked dim and the microbatch ring shifts via ``jnp.roll`` on it
  (GSPMD emits the collective-permute) — the 1F1B-equivalent schedule with
  bubble (S-1)/(M+S-1), with every mesh axis staying GSPMD-automatic.

Everything is a pure function over a params pytree -> works under jit, grad,
and donation; the single entry is :func:`build_spmd_train_step`.
"""
from __future__ import annotations

import math

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .gpt import GPTConfig


# the ONE mesh-shape heuristic lives in distributed.mesh (round 11 —
# serving shares it); these names stay importable from here
from ..distributed.mesh import (choose_mesh_shape,  # noqa: F401
                                make_training_mesh as make_mesh)


# ---------------------------------------------------------------------------
# Parameter init + shardings
# ---------------------------------------------------------------------------


def init_params(config: GPTConfig, mesh: Mesh, seed: int = 0, dtype=jnp.float32):
    pp = mesh.shape["pp"]
    assert config.num_layers % pp == 0, "num_layers must divide pp"
    lps = config.num_layers // pp
    h, f, v, s = config.hidden_size, config.ffn_size, config.vocab_size, config.max_seq_len
    std = config.initializer_range
    key = jax.random.PRNGKey(seed)
    ks = iter(jax.random.split(key, 16))

    def norm(k, shape):
        return (jax.random.normal(k, shape, jnp.float32) * std).astype(dtype)

    stages = {
        "ln1_g": jnp.ones((pp, lps, h), dtype),
        "ln1_b": jnp.zeros((pp, lps, h), dtype),
        "wqkv": norm(next(ks), (pp, lps, h, 3 * h)),
        "bqkv": jnp.zeros((pp, lps, 3 * h), dtype),
        "wo": norm(next(ks), (pp, lps, h, h)),
        "bo": jnp.zeros((pp, lps, h), dtype),
        "ln2_g": jnp.ones((pp, lps, h), dtype),
        "ln2_b": jnp.zeros((pp, lps, h), dtype),
    }
    e = int(getattr(config, "moe_experts", 0) or 0)
    if e:
        # MoE block: router gate + stacked expert FFNs replace the dense
        # MLP (the leading [E] expert dim shards over "ep" when present)
        stages.update({
            "moe_gate": norm(next(ks), (pp, lps, h, e)),
            "moe_w1": norm(next(ks), (pp, lps, e, h, f)),
            "moe_b1": jnp.zeros((pp, lps, e, f), dtype),
            "moe_w2": norm(next(ks), (pp, lps, e, f, h)),
            "moe_b2": jnp.zeros((pp, lps, e, h), dtype),
        })
    else:
        stages.update({
            "w1": norm(next(ks), (pp, lps, h, f)),
            "b1": jnp.zeros((pp, lps, f), dtype),
            "w2": norm(next(ks), (pp, lps, f, h)),
            "b2": jnp.zeros((pp, lps, h), dtype),
        })
    params = {
        "tok_emb": norm(next(ks), (v, h)),
        "pos_emb": norm(next(ks), (s, h)),
        "stages": stages,
        "lnf_g": jnp.ones((h,), dtype),
        "lnf_b": jnp.zeros((h,), dtype),
    }
    return params


def param_specs(moe: bool = False, ep_axis: str | None = None) -> dict:
    """PartitionSpecs: pp stacks stages, mp is the Megatron dim.

    ``moe=True`` swaps the dense-MLP rows for the expert stacks;
    ``ep_axis`` ("ep" on the round-25 4-axis mesh, None on a 3-axis one)
    shards the expert dim — the mp axis stays on attention only (expert
    GEMMs are already parallel over experts)."""
    stages = {
        "ln1_g": P("pp", None, None),
        "ln1_b": P("pp", None, None),
        "wqkv": P("pp", None, None, "mp"),   # column parallel
        "bqkv": P("pp", None, "mp"),
        "wo": P("pp", None, "mp", None),     # row parallel
        "bo": P("pp", None, None),
        "ln2_g": P("pp", None, None),
        "ln2_b": P("pp", None, None),
    }
    if moe:
        stages.update({
            "moe_gate": P("pp", None, None, None),
            "moe_w1": P("pp", None, ep_axis, None, None),
            "moe_b1": P("pp", None, ep_axis, None),
            "moe_w2": P("pp", None, ep_axis, None, None),
            "moe_b2": P("pp", None, ep_axis, None),
        })
    else:
        stages.update({
            "w1": P("pp", None, None, "mp"),
            "b1": P("pp", None, "mp"),
            "w2": P("pp", None, "mp", None),
            "b2": P("pp", None, None),
        })
    return {
        "tok_emb": P("mp", None),  # vocab-parallel embedding
        "pos_emb": P(),
        "stages": stages,
        "lnf_g": P(),
        "lnf_b": P(),
    }


def _specs_for(params, mesh: Mesh) -> dict:
    """The spec tree matching a params pytree on this mesh (MoE and the
    ep axis inferred — keeps every pre-MoE caller signature intact)."""
    moe = "moe_w1" in params["stages"]
    ep_axis = "ep" if (moe and "ep" in mesh.axis_names
                       and mesh.shape["ep"] > 1) else None
    return param_specs(moe=moe, ep_axis=ep_axis)


def param_shardings(mesh: Mesh, params=None):
    specs = (param_specs() if params is None
             else _specs_for(params, mesh))
    return jax.tree.map(
        lambda spec: NamedSharding(mesh, spec),
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def _add_dp_dim(spec: P, shape, dp: int) -> P:
    """Extend ``spec`` with "dp" on the first unsharded dim divisible by dp.

    The compiled-ZeRO primitive: sharding a state tensor over the data axis
    is exactly the reference's DygraphShardingOptimizer parameter split
    (dygraph_sharding_optimizer.py) — XLA inserts the all-gather on use and
    reduce-scatter on update that stages 1-3 hand-code."""
    if dp <= 1:
        return spec
    parts = list(spec) + [None] * (len(shape) - len(spec))
    for i, (ps, sz) in enumerate(zip(parts, shape)):
        if ps is None and sz % dp == 0:
            parts[i] = "dp"
            return P(*parts)
    return spec  # nothing divides: stays replicated (small biases/norms)


def zero_shardings(params, mesh: Mesh, stage: int):
    """(param shardings, optimizer-state shardings) for ZeRO stage 0-3.

    stage>=1: optimizer state sharded over dp (ZeRO-1; reference
    DygraphShardingOptimizer). stage>=2: gradients are reduce-scattered by
    GSPMD as a consequence of the state shardings (ZeRO-2; reference
    GroupShardedOptimizerStage2 — in the compiled world XLA chooses
    reduce-scatter over all-reduce when the consumer is dp-sharded).
    stage>=3: parameters themselves sharded over dp, gathered on use
    (ZeRO-3; reference GroupShardedStage3 pre-forward allgather)."""
    dp = mesh.shape["dp"]
    base = _specs_for(params, mesh)

    def opt_spec(spec, leaf):
        return NamedSharding(mesh, _add_dp_dim(spec, leaf.shape, dp))

    specs_flat = jax.tree.leaves(base, is_leaf=lambda x: isinstance(x, P))
    leaves_flat = jax.tree.leaves(params)
    treedef = jax.tree.structure(params)
    opt = treedef.unflatten(
        [opt_spec(s, l) for s, l in zip(specs_flat, leaves_flat)])
    if stage >= 3:
        p_shard = opt
    else:
        p_shard = treedef.unflatten(
            [NamedSharding(mesh, s) for s in specs_flat])
    return p_shard, (opt if stage >= 1 else p_shard)


# ---------------------------------------------------------------------------
# Model math (pure, global-view except the pp ring)
# ---------------------------------------------------------------------------


def _layer_norm(x, g, b, eps):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mu) * lax.rsqrt(var + eps) * g + b


def _fused_mlp_on(config: GPTConfig, mesh: Mesh) -> bool:
    """Whether the fused MLP-block Pallas kernels (ops/pallas/fused_mlp)
    replace the XLA elementwise chains in this block. GSPMD cannot
    partition a pallas_call, so the fused path is single-shard only —
    exactly the flagship 1-chip config it targets. Off-TPU the kernels run
    in interpret mode and only when ``force_fused_mlp`` asks for them
    (CPU tests); a compiled CPU run would pay interpreter dispatch."""
    if not getattr(config, "fused_mlp", False):
        return False
    if getattr(config, "moe_experts", 0):
        return False  # the fused MLP kernels are dense-only
    if math.prod(mesh.shape.values()) != 1:
        return False
    if jax.default_backend() != "tpu":
        return bool(getattr(config, "force_fused_mlp", False))
    return True


def _mk_cs(mesh: Mesh):
    # Plain PartitionSpecs resolve against the context mesh (jax.set_mesh),
    # so the same constraints hold inside vmapped/scanned bodies where a
    # concrete NamedSharding's rank could mismatch the batched view.
    def cs(x, spec):
        return lax.with_sharding_constraint(x, spec)

    return cs


def _block(p, x, config: GPTConfig, mesh: Mesh, dp_axis="dp"):
    """One decoder block on [mb, s, h] with TP/SP sharding constraints.
    Returns ``(x, aux)`` — the MoE load-balance loss for this layer (0.0
    on dense configs), accumulated up the scan/pipeline.

    ``dp_axis=None`` drops the batch-dim constraints: the comm-quant dp
    train step vmaps this math over an explicit replica dim (the leading
    stacked dim carries the "dp" sharding), so binding "dp" again inside
    would double-use the mesh axis."""
    nh, hd = config.num_heads, config.head_dim
    mb, s, h = x.shape
    cs = _mk_cs(mesh)

    fused = _fused_mlp_on(config, mesh)
    # SP region: sequence sharded over mp
    x = cs(x, P(dp_axis, "mp", None))
    if "attn" in config.ablate:  # perf attribution: skip the whole branch
        return _block_mlp(p, x, config, cs, dp_axis, mesh)
    if fused:
        from ..ops.pallas import fused_mlp as _fm

        # single-pass LN kernel (fp32 stats, mean/rstd saved for backward;
        # tags its outputs "ln_out" so remat_save_ln keeps working)
        y = _fm.fused_layer_norm(x, p["ln1_g"], p["ln1_b"],
                                 eps=config.layer_norm_eps, use_kernel=True)
    else:
        y = _layer_norm(x, p["ln1_g"], p["ln1_b"], config.layer_norm_eps)
    if not fused and getattr(config, "remat_save_ln", False):
        from jax.ad_checkpoint import checkpoint_name

        y = checkpoint_name(y, "ln_out")
    qkv = y @ p["wqkv"] + p["bqkv"]           # column-parallel -> [mb,s,3h]/mp
    qkv = cs(qkv, P(dp_axis, None, "mp"))
    q, k, v = jnp.split(qkv, 3, axis=-1)

    def heads(t):  # [mb, s, h] -> [mb, nh, s, hd], heads sharded over mp
        t = t.reshape(mb, s, nh, hd).transpose(0, 2, 1, 3)
        return cs(t, P(dp_axis, "mp", None, None))

    if jax.default_backend() == "tpu":
        use_flash = config.use_flash_attention and s % 128 == 0
    else:
        use_flash = config.force_flash  # interpret-mode kernel for CPU tests
    if use_flash:
        # fused Pallas kernel: no S x S residuals in fwd or bwd. Under TP the
        # kernel runs per-device via shard_map over the mp-sharded head dim
        # (and dp-sharded batch): heads are embarrassingly parallel in flash
        # attention, so no collectives are needed inside the region —
        # reference never runs flash under mp>1 shards a head *across*
        # devices either (mp_layers.py splits by whole heads).
        from ..ops.pallas.flash_attention import flash_attention

        qh = q.reshape(mb, s, nh, hd)
        kh = k.reshape(mb, s, nh, hd)
        vh = v.reshape(mb, s, nh, hd)
        sharded_dp = dp_axis is not None and mesh.shape["dp"] > 1
        if mesh.shape["mp"] > 1 or sharded_dp:
            spec = P(dp_axis, None, "mp", None)

            def local_flash(qs, ks, vs):
                return flash_attention(qs, ks, vs, causal=True)

            o = jax.shard_map(
                local_flash,
                in_specs=(spec, spec, spec),
                out_specs=spec,
                axis_names={"mp"} | ({"dp"} if sharded_dp else set()),
                check_vma=False,
            )(qh, kh, vh)
        else:
            o = flash_attention(qh, kh, vh, causal=True)
        o = o.reshape(mb, s, h)
    else:
        q, k, v = heads(q), heads(k), heads(v)
        scores = jnp.einsum("bnqd,bnkd->bnqk", q, k) / math.sqrt(hd)
        causal = jnp.tril(jnp.ones((s, s), bool))
        scores = jnp.where(causal, scores, -1e30)
        attn = jax.nn.softmax(scores, axis=-1)
        o = jnp.einsum("bnqk,bnkd->bnqd", attn, v)
        o = o.transpose(0, 2, 1, 3).reshape(mb, s, h)
    o = o @ p["wo"] + p["bo"]                  # row-parallel
    if fused:
        return _block_mlp_fused(p, x, o, config), jnp.float32(0.0)
    x = x + cs(o, P(dp_axis, "mp", None))      # reduce-scatter onto SP layout
    return _block_mlp(p, x, config, cs, dp_axis, mesh)


def _block_mlp_fused(p, x, branch, config: GPTConfig):
    """Fused-kernel MLP half (single shard): the attention branch's residual
    add + LN2 ride ONE residual-in/residual-out kernel (one HBM round-trip
    instead of three), and fc1's bias+gelu ride one epilogue kernel after
    the GEMM — the round-5 roofline's ~1.3 ms/layer of elementwise traffic."""
    from ..ops.pallas import fused_mlp as _fm

    if "mlp" in config.ablate:  # perf attribution: skip the whole branch
        return x + branch
    y, s = _fm.fused_ln_residual(branch, x, p["ln2_g"], p["ln2_b"],
                                 eps=config.layer_norm_eps, use_kernel=True)
    y = _fm.fused_bias_gelu(y @ p["w1"], p["b1"], use_kernel=True)
    return s + (y @ p["w2"] + p["b2"])


def _block_mlp(p, x, config: GPTConfig, cs, dp_axis="dp", mesh=None):
    if "mlp" in config.ablate:  # perf attribution: skip the whole branch
        return x, jnp.float32(0.0)
    y = _layer_norm(x, p["ln2_g"], p["ln2_b"], config.layer_norm_eps)
    if getattr(config, "remat_save_ln", False):
        from jax.ad_checkpoint import checkpoint_name

        y = checkpoint_name(y, "ln_out")
    if getattr(config, "moe_experts", 0):
        return _moe_mlp(p, x, y, config, cs, dp_axis, mesh)
    y = jax.nn.gelu(y @ p["w1"] + p["b1"], approximate=True)
    y = cs(y, P(dp_axis, None, "mp"))
    y = y @ p["w2"] + p["b2"]
    x = x + cs(y, P(dp_axis, "mp", None))
    return x, jnp.float32(0.0)


def _moe_mlp(p, x, y, config: GPTConfig, cs, dp_axis, mesh):
    """The expert-sharded MoE FFN half of a block: GShard dense-mask
    gating (``models.moe.topk_dispatch_combine`` — the einsum twin of the
    serving grouped-GEMM path, same routing/capacity/tie-break math) with
    the expert dim sharded over "ep".

    Dispatch is collective-FREE: ``y`` is replicated over ep, so each ep
    shard builds its local experts' [E/ep, C, d] buffers with a slice of
    the dispatch mask. The COMBINE is the wire: each shard's partial
    outputs stack [ep, n, d] and reduce over the ep ring through the
    PR-9 int8 wire-quant surface (``quantized_all_reduce_stacked``) —
    ~4x fewer bytes than an fp all-reduce, s8 collectives on the HLO
    (the JX009 contract). ep == 1 keeps plain einsums, no collectives."""
    from ..distributed.compressed_collectives import (
        quantized_all_reduce_stacked)
    from .moe import moe_capacity, topk_dispatch_combine

    mb, s, h = y.shape
    e = int(config.moe_experts)
    n = mb * s
    tok = y.reshape(n, h)
    logits = tok.astype(jnp.float32) @ p["moe_gate"].astype(jnp.float32)
    cap = moe_capacity(n, e, config.moe_top_k, config.moe_capacity_factor)
    combine, dispatch, aux = topk_dispatch_combine(
        logits, cap, config.moe_top_k)
    ep = 1
    if mesh is not None and "ep" in mesh.axis_names:
        ep = mesh.shape["ep"]
    expert_in = jnp.einsum("nec,nd->ecd", dispatch.astype(tok.dtype), tok)
    if ep > 1:
        expert_in = cs(expert_in, P("ep", None, None))
    hmid = jax.nn.gelu(
        jnp.einsum("ecd,edf->ecf", expert_in, p["moe_w1"])
        + p["moe_b1"][:, None, :], approximate=True)
    expert_out = (jnp.einsum("ecf,efd->ecd", hmid, p["moe_w2"])
                  + p["moe_b2"][:, None, :])
    if ep > 1:
        expert_out = cs(expert_out, P("ep", None, None))
        eg = e // ep
        out_g = expert_out.reshape(ep, eg, cap, h)
        comb_g = combine.reshape(n, ep, eg, cap).transpose(1, 0, 2, 3)
        partial = jnp.einsum("gnec,gecd->gnd", comb_g.astype(tok.dtype),
                             out_g)
        partial = cs(partial, P("ep", None, None))
        # [ep, n, d] in, every slot the ring sum out — take slot 0
        out = quantized_all_reduce_stacked(partial, mesh=mesh, axis="ep",
                                           mean=False)[0]
    else:
        out = jnp.einsum("nec,ecd->nd", combine.astype(tok.dtype),
                         expert_out)
    out = out.reshape(mb, s, h).astype(x.dtype)
    x = x + cs(out, P(dp_axis, "mp", None))
    return x, aux


def _stage_fn(p_stage, x, config: GPTConfig, mesh: Mesh, dp_axis="dp"):
    """Apply this pp rank's layers (scan over the layer-in-stage dim).

    With ``config.recompute`` the block is rematerialized in backward
    (activations per layer drop from ~6 stacked [mb,s,4h] buffers to the
    layer input — SURVEY §2.7 recompute strategy; on TPU this is what lets
    batch scale past HBM), at ~30% recompute FLOPs. Matmul outputs are kept
    (checkpoint_dots policy) so the MXU work is not redone.
    """

    def body(carry, p_layer):
        x, aux = carry
        x2, a = _block(p_layer, x, config, mesh, dp_axis)
        return (x2, aux + a), None

    if getattr(config, "recompute", False):
        # weight-GEMM outputs AND (by default) the flash kernel's o/lse are
        # saved; the backward recomputes only elementwise/LN (cheap) —
        # remat trades the minimum FLOPs for the activation-memory win
        policy = jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims
        names = []
        if getattr(config, "remat_save_attn", True):
            names.append("flash_out")
        if getattr(config, "remat_save_ln", False):
            names.append("ln_out")
        if names:
            policy = jax.checkpoint_policies.save_from_both_policies(
                policy,
                jax.checkpoint_policies.save_only_these_names(*names))
        body = jax.checkpoint(body, policy=policy)
    (x, aux), _ = lax.scan(body, (x, jnp.float32(0.0)), p_stage)
    return x, aux


def _pipeline(stages, mbs, mesh: Mesh, config: GPTConfig, dp_axis="dp"):
    """Microbatch pipeline over the pp axis (GSPMD-pipelined stacked stages).

    stages: pytree with leading [pp, lps, ...] dims. mbs: [M, mb, s, h].
    Returns ``([M, mb, s, h], aux)`` — last-stage outputs (replicated
    over pp) and the MoE aux loss summed over every (microbatch, layer)
    the SCHEDULE actually ran (the warm-up/drain garbage slots mask out;
    0.0 on dense configs).

    Roll formulation (praxis-style GSPMD pipelining): every stage computes
    in parallel under ``vmap`` over the pp-sharded stacked dim, and the ring
    shift is ``jnp.roll`` on that dim — GSPMD emits the collective-permute
    itself and every mesh axis stays automatic. The earlier partial-manual
    ``shard_map`` ring is gone: ``lax.axis_index``/``lax.ppermute`` inside a
    partially-auto manual region lower through PartitionId / mismatched
    manual-subgroup shardings that the jax-0.4.x SPMD partitioner rejects
    (CPU: hard UNIMPLEMENTED / partitioner check failure).
    """
    num_stages = mesh.shape["pp"]
    num_micro = mbs.shape[0]
    if num_stages == 1:
        p_one = jax.tree.map(lambda a: a[0], stages)

        def one(mb):
            return _stage_fn(p_one, mb, config, mesh, dp_axis)

        ys, auxs = jax.lax.map(one, mbs)
        return ys, jnp.sum(auxs)

    total = num_micro + num_stages - 1
    last = num_stages - 1
    cs = _mk_cs(mesh)

    stage_v = jax.vmap(lambda p, x: _stage_fn(p, x, config, mesh, dp_axis))

    def step(carry, t):
        # inject microbatch t into stage 0 (clipped past the schedule; the
        # recycled garbage is never collected), run ALL stages in parallel,
        # shift stage s's output to stage s+1's next input via the roll
        acts = carry.at[0].set(mbs[jnp.clip(t, 0, num_micro - 1)])
        acts = cs(acts, P("pp", dp_axis, None, None))
        y, aux = stage_v(stages, acts)
        return jnp.roll(y, 1, axis=0), (y[last], aux)

    init = jnp.zeros((num_stages,) + mbs.shape[1:], mbs.dtype)
    _, (outs, auxs) = lax.scan(step, init,
                               jnp.arange(total, dtype=jnp.int32))
    # stage s at time t runs microbatch t - s; everything else in the
    # warm-up/drain window is recycled garbage — mask its aux out
    t_idx = jnp.arange(total)[:, None]
    s_idx = jnp.arange(num_stages)[None, :]
    sched = ((t_idx - s_idx >= 0)
             & (t_idx - s_idx < num_micro)).astype(jnp.float32)
    # microbatch m reaches the last stage at t = m + (S-1)
    return outs[last : last + num_micro], jnp.sum(auxs * sched)


def loss_fn(params, ids, labels, config: GPTConfig, mesh: Mesh, num_micro: int,
            dp_axis="dp"):
    # MXU-native matmul precision: the framework default is "highest" (true
    # fp32 semantics for user-facing float32 ops), which would emulate even
    # bf16 matmuls with multi-pass fp32 — 6x slower. The training path wants
    # native bf16 MXU passes; loss math below is explicitly fp32.
    # dp_axis=None: the comm-quant step vmaps this over an explicit replica
    # dim, so the batch constraints must not re-bind the "dp" mesh axis.
    with jax.default_matmul_precision("default"):
        return _loss_fn_inner(params, ids, labels, config, mesh, num_micro,
                              dp_axis)


def _loss_fn_inner(params, ids, labels, config: GPTConfig, mesh: Mesh,
                   num_micro: int, dp_axis="dp"):
    cs = _mk_cs(mesh)
    b, s = ids.shape
    x = jnp.take(params["tok_emb"], ids, axis=0) + params["pos_emb"][:s]
    x = cs(x, P(dp_axis, None, None))
    mb = b // num_micro
    mbs = x.reshape(num_micro, mb, s, x.shape[-1])
    y, moe_aux = _pipeline(params["stages"], mbs, mesh, config, dp_axis)
    y = y.reshape(b, s, -1)
    y = _layer_norm(y, params["lnf_g"], params["lnf_b"], config.layer_norm_eps)

    # Shifted next-token CE, chunked over the sequence with remat: the full
    # [b, s, vocab] fp32 logits (3.2 GB at bs16/seq1024/50k vocab) never
    # materialize — each chunk's logits are recomputed in backward. Costs one
    # extra head matmul pass (~2hv/token, ~8% of step FLOPs at 125M) and
    # buys 2-4x batch on a 16 GB chip, a clear MFU win.
    emb = params["tok_emb"]
    # shift labels left; the last position has no target (masked below)
    lb = jnp.concatenate([labels[:, 1:], labels[:, :1]], axis=1)
    chunk = s
    while chunk > 128 or s % chunk:
        chunk //= 2
    nchunks = s // chunk
    yc = y.reshape(b, nchunks, chunk, -1).transpose(1, 0, 2, 3)
    lbc = lb.reshape(b, nchunks, chunk).transpose(1, 0, 2)

    def chunk_nll(args):
        y_ch, lb_ch = args
        lg = (y_ch @ emb.T).astype(jnp.float32)  # [b, chunk, v]
        lg = cs(lg, P(dp_axis, None, "mp"))  # vocab-sharded over mp (tied head)
        if "ce" in config.ablate:
            # perf attribution: keep the head matmul (and the chunked remat
            # structure), drop the softmax-CE math
            return jnp.sum(lg, axis=-1) * 1e-9
        lse = jax.scipy.special.logsumexp(lg, axis=-1)
        tgt = jnp.take_along_axis(lg, lb_ch[..., None], axis=-1)[..., 0]
        return lse - tgt  # [b, chunk]

    nll = lax.map(jax.checkpoint(chunk_nll), (yc, lbc))  # [nchunks, b, chunk]
    nll = nll.transpose(1, 0, 2).reshape(b, s)
    valid = (jnp.arange(s) < s - 1).astype(jnp.float32)
    loss = jnp.sum(nll * valid) / (b * (s - 1))
    if getattr(config, "moe_experts", 0):
        # mean aux per (layer, microbatch), weighted into the objective
        loss = loss + (getattr(config, "moe_aux_weight", 0.01)
                       * moe_aux / (num_micro * config.num_layers))
    return loss


# ---------------------------------------------------------------------------
# Train step
# ---------------------------------------------------------------------------


def sgd_init(params):
    return jax.tree.map(jnp.zeros_like, params)


def build_spmd_train_step(
    config: GPTConfig,
    mesh: Mesh,
    batch_size: int,
    seq_len: int,
    num_micro: int | None = None,
    lr: float = 1e-3,
    momentum: float = 0.9,
    zero_stage: int = 0,
    comm_quant=None,
):
    """Returns (jitted step, params, opt_state, example (ids, labels)).

    The step is jit-compiled over the mesh with full in/out shardings and
    donated state: ``step(params, momentum, ids, labels) -> (params, momentum,
    loss)``. ``zero_stage`` 1-3 shards optimizer state (and for 3, params)
    over the dp axis — see :func:`zero_shardings`.

    ``comm_quant`` ("int8" or a ``CommQuantConfig``) replaces the implicit
    GSPMD gradient allreduce over ``dp`` with the EXPLICIT int8 quantized
    ring of ``distributed.compressed_collectives``: per-replica gradients
    are computed stacked (``vmap`` over the dp-sharded replica dim, the
    model math running with ``dp_axis=None``), bucketed, ring-reduced with
    deterministic per-hop requantization and decoded identically on every
    replica — ~4x fewer gradient bytes on the interconnect. With
    ``zero_stage >= 2`` the decoded gradient feeds the dp-sharded state
    update (GSPMD slices the replicated decode into the reduce-scattered
    consumption — same bytes, ZeRO placements preserved).
    """
    from ..distributed.compressed_collectives import (
        as_comm_quant_config, quantized_all_reduce_pytree)

    num_micro = num_micro or max(1, 2 * mesh.shape["pp"])
    assert batch_size % num_micro == 0
    dp = mesh.shape["dp"]
    cq = as_comm_quant_config(comm_quant)
    use_cq = cq is not None and dp > 1
    if use_cq:
        if batch_size % (dp * num_micro):
            raise ValueError(
                f"comm_quant needs batch_size {batch_size} divisible by "
                f"dp * num_micro = {dp} * {num_micro}")

    if getattr(config, "moe_experts", 0):
        ep = mesh.shape.get("ep", 1) if "ep" in mesh.axis_names else 1
        if ep > 1 and config.moe_experts % ep:
            raise ValueError(
                f"moe_experts={config.moe_experts} must divide the ep "
                f"mesh axis ({ep}) — each ep shard owns whole experts")
        if getattr(config, "fused_mlp", False):
            raise ValueError(
                "fused_mlp has no MoE path — the fused MLP kernels are "
                "dense-only (disable fused_mlp for moe_experts > 0)")
    params = init_params(config, mesh)
    if zero_stage:
        p_shard, m_shard = zero_shardings(params, mesh, zero_stage)
    else:
        p_shard = m_shard = param_shardings(mesh, params)
    params = jax.device_put(params, p_shard)
    mom = jax.device_put(sgd_init(params), m_shard)
    data_shard = NamedSharding(mesh, P("dp", None))

    def sync_grads(params, ids, labels):
        """(loss, synced grads): implicit GSPMD allreduce, or the explicit
        int8 quantized ring when comm_quant is on."""
        if not use_cq:
            return jax.value_and_grad(loss_fn)(
                params, ids, labels, config, mesh, num_micro)
        # explicit dp sync: stack the batch replica-major, compute each
        # replica's local gradient under vmap (dp_axis=None — the stacked
        # dim carries the dp sharding), then ring-reduce int8 chunks
        st = NamedSharding(mesh, P("dp", None, None))
        ids_st = lax.with_sharding_constraint(
            ids.reshape(dp, batch_size // dp, seq_len), st)
        lbl_st = lax.with_sharding_constraint(
            labels.reshape(dp, batch_size // dp, seq_len), st)

        def local_grad(i, l):
            return jax.value_and_grad(loss_fn)(
                params, i, l, config, mesh, num_micro, None)

        losses, g_st = jax.vmap(local_grad)(ids_st, lbl_st)
        g_st = jax.tree.map(
            lambda g: lax.with_sharding_constraint(
                g, NamedSharding(mesh, P("dp"))), g_st)
        grads = quantized_all_reduce_pytree(g_st, mesh=mesh, axis="dp",
                                            cfg=cq, mean=True)
        return jnp.mean(losses), grads

    def step(params, mom, ids, labels):
        loss, grads = sync_grads(params, ids, labels)
        mom2 = jax.tree.map(lambda m, g: momentum * m + g, mom, grads)
        params2 = jax.tree.map(lambda p, m: p - lr * m, params, mom2)
        return params2, mom2, loss

    jitted_inner = jax.jit(
        step,
        in_shardings=(p_shard, m_shard, data_shard, data_shard),
        out_shardings=(p_shard, m_shard, NamedSharding(mesh, P())),
        donate_argnums=(0, 1),
    )

    # round-15 telemetry on the library-wide registry (off by default;
    # observability.enable_metrics() turns it on): step counter, host
    # dispatch seconds, and the analytic per-replica dp gradient-sync
    # wire bytes per step (the round-14 bytes_on_the_wire ring model,
    # labeled by wire dtype) — so a long training run's interconnect
    # spend is a snapshot read, not a post-hoc estimate
    from ..distributed.compressed_collectives import bytes_on_the_wire
    from ..observability import default_registry, monotonic, tracing_active
    from ..observability import span as _span

    _m_steps = default_registry.counter(
        "train_steps", "spmd train-step invocations")
    _m_host_s = default_registry.counter(
        "train_dispatch_seconds", "host seconds dispatching train steps")
    _m_wire = default_registry.counter(
        "train_wire_bytes", "per-replica dp gradient-sync wire bytes",
        labels=("quant",)).labels(quant="int8" if use_cq else "fp")
    wire_per_step = 0
    if dp > 1:
        wire_per_step = sum(
            bytes_on_the_wire(int(np.prod(l.shape)), int(dp),
                              elem_bytes=jnp.dtype(l.dtype).itemsize,
                              quant=cq if use_cq else None)
            for l in jax.tree.leaves(params))

    # the first call through the jit traces + XLA-compiles (seconds);
    # charging that to "dispatch seconds" would make the per-step read
    # compile-dominated, so the timer starts at the second call
    _compiled = [False]

    def jitted(*args):
        # metrics (registry) and tracing (profiler window) are
        # independent toggles: profiling a training run must record the
        # span even with the registry off, and vice versa
        if not (default_registry.enabled or tracing_active()):
            with jax.set_mesh(mesh):
                out = jitted_inner(*args)
            _compiled[0] = True
            return out
        t0 = monotonic()
        with _span("spmd_train_step", category="train"):
            with jax.set_mesh(mesh):
                out = jitted_inner(*args)
        _m_steps.inc()
        if _compiled[0]:
            _m_host_s.inc(monotonic() - t0)
        _compiled[0] = True
        _m_wire.inc(wire_per_step)
        return out

    jitted.lower = lambda *a: jitted_inner.lower(*a)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, config.vocab_size, (batch_size, seq_len)), jnp.int32)
    labels = jnp.asarray(rng.randint(0, config.vocab_size, (batch_size, seq_len)), jnp.int32)
    ids = jax.device_put(ids, data_shard)
    labels = jax.device_put(labels, data_shard)
    return jitted, params, mom, (ids, labels)
