"""ERNIE model family (ERNIE-3.0-class encoder).

Architecture parity: the ERNIE encoder the reference ecosystem trains (the
BASELINE.md ERNIE-3.0 config ladder): BERT-style post-LN transformer
encoder with word/position/token-type/task-type embeddings (task-type being
ERNIE's addition), GELU MLP, pooled [CLS] head, plus MLM/NSP pretraining
heads. Attention via F.scaled_dot_product_attention (flash attention on
TPU).

Tensor parallelism mirrors the llama/GPT families (the reference trains
ERNIE under fleet hybrid parallel the same way): fused qkv and MLP-in are
column-parallel, attention-out and MLP-out are row-parallel, and the word
embedding is vocab-parallel when an mp group is active (Megatron layout,
reference mp_layers.py:47/:333/:540).
"""
from __future__ import annotations

from dataclasses import dataclass

from ..framework.param_attr import ParamAttr
from ..nn import Layer, functional as F
from ..nn.initializer import Normal
from ..nn.layer.common import Dropout, Embedding, Linear
from ..nn.layer.container import LayerList
from ..nn.layer.norm import LayerNorm
from ..tensor.creation import arange, zeros_like
from ..tensor.manipulation import reshape
from ..tensor.math import matmul
from ._tp import mp_degree as _mp_degree, tp_enabled as _tp_enabled


@dataclass
class ErnieConfig:
    vocab_size: int = 40000
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 2048
    type_vocab_size: int = 4
    task_type_vocab_size: int = 16
    use_task_id: bool = True
    hidden_dropout: float = 0.0
    attn_dropout: float = 0.0
    layer_norm_eps: float = 1e-12
    initializer_range: float = 0.02
    tensor_parallel: bool = False


ERNIE_CONFIGS: dict[str, ErnieConfig] = {
    "ernie-tiny": ErnieConfig(vocab_size=1024, hidden_size=128, num_layers=2,
                              num_heads=4, intermediate_size=512,
                              max_position_embeddings=128),
    "ernie-3.0-base": ErnieConfig(),
    "ernie-3.0-medium": ErnieConfig(num_layers=6),
    "ernie-3.0-xbase": ErnieConfig(hidden_size=1024, num_layers=20,
                                   num_heads=16, intermediate_size=4096),
}


def _w(config: ErnieConfig) -> ParamAttr:
    return ParamAttr(initializer=Normal(mean=0.0,
                                        std=config.initializer_range))


def _linear(config, in_f, out_f, kind):
    """kind: 'col' (shard output dim) | 'row' (shard input dim) | 'plain'.
    ERNIE linears keep their biases (BERT lineage)."""
    from ._tp import tp_linear

    return tp_linear(config, in_f, out_f, kind, _w(config), has_bias=True)


class ErnieEmbeddings(Layer):
    def __init__(self, config: ErnieConfig):
        super().__init__()
        if _tp_enabled(config):
            from ..distributed.fleet.meta_parallel.mp_layers import (
                VocabParallelEmbedding,
            )

            self.word_embeddings = VocabParallelEmbedding(
                config.vocab_size, config.hidden_size, weight_attr=_w(config))
        else:
            self.word_embeddings = Embedding(config.vocab_size,
                                             config.hidden_size,
                                             weight_attr=_w(config))
        self.position_embeddings = Embedding(config.max_position_embeddings,
                                             config.hidden_size,
                                             weight_attr=_w(config))
        self.token_type_embeddings = Embedding(config.type_vocab_size,
                                               config.hidden_size,
                                               weight_attr=_w(config))
        self.task_type_embeddings = (
            Embedding(config.task_type_vocab_size, config.hidden_size,
                      weight_attr=_w(config)) if config.use_task_id else None)
        self.layer_norm = LayerNorm(config.hidden_size,
                                    epsilon=config.layer_norm_eps)
        self.dropout = Dropout(config.hidden_dropout)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                task_type_ids=None):
        S = input_ids.shape[1]
        if position_ids is None:
            position_ids = arange(0, S, dtype="int64").unsqueeze(0)
        if token_type_ids is None:
            token_type_ids = zeros_like(input_ids)
        x = (self.word_embeddings(input_ids)
             + self.position_embeddings(position_ids)
             + self.token_type_embeddings(token_type_ids))
        if self.task_type_embeddings is not None:
            if task_type_ids is None:
                task_type_ids = zeros_like(input_ids)
            x = x + self.task_type_embeddings(task_type_ids)
        return self.dropout(self.layer_norm(x))


class ErnieSelfAttention(Layer):
    def __init__(self, config: ErnieConfig):
        super().__init__()
        self.config = config
        h = config.hidden_size
        if _tp_enabled(config):
            ws = max(_mp_degree(), 1)
            if config.num_heads % ws:
                raise ValueError(
                    f"tensor parallel degree {ws} must divide num_heads "
                    f"{config.num_heads}")
        self.qkv = _linear(config, h, 3 * h, "col")
        self.out = _linear(config, h, h, "row")

    def forward(self, x, attn_mask=None):
        cfg = self.config
        B, S, _ = x.shape
        hd = cfg.hidden_size // cfg.num_heads
        qkv = reshape(self.qkv(x), [B, S, 3, cfg.num_heads, hd])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask,
            dropout_p=cfg.attn_dropout, training=self.training)
        return self.out(reshape(out, [B, S, cfg.hidden_size]))


class ErnieEncoderLayer(Layer):
    """Post-LN block (BERT/ERNIE convention)."""

    def __init__(self, config: ErnieConfig):
        super().__init__()
        h = config.hidden_size
        self.self_attn = ErnieSelfAttention(config)
        self.norm1 = LayerNorm(h, epsilon=config.layer_norm_eps)
        self.linear1 = _linear(config, h, config.intermediate_size, "col")
        self.linear2 = _linear(config, config.intermediate_size, h, "row")
        self.norm2 = LayerNorm(h, epsilon=config.layer_norm_eps)
        self.dropout = Dropout(config.hidden_dropout)

    def forward(self, x, attn_mask=None):
        x = self.norm1(x + self.dropout(self.self_attn(x, attn_mask)))
        mlp = self.linear2(F.gelu(self.linear1(x)))
        return self.norm2(x + self.dropout(mlp))


class ErniePooler(Layer):
    def __init__(self, config: ErnieConfig):
        super().__init__()
        self.dense = Linear(config.hidden_size, config.hidden_size,
                            weight_attr=_w(config))

    def forward(self, hidden):
        return F.tanh(self.dense(hidden[:, 0]))


class ErnieModel(Layer):
    def __init__(self, config: ErnieConfig):
        super().__init__()
        self.config = config
        self.embeddings = ErnieEmbeddings(config)
        self.encoder = LayerList(
            [ErnieEncoderLayer(config) for _ in range(config.num_layers)])
        self.pooler = ErniePooler(config)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None, task_type_ids=None):
        if attention_mask is not None and attention_mask.ndim == 2:
            # [B, S] 1/0 -> additive [B, 1, 1, S]
            attention_mask = (
                (1.0 - attention_mask.astype("float32")) * -1e4
            ).unsqueeze(1).unsqueeze(2)
        x = self.embeddings(input_ids, token_type_ids, position_ids,
                            task_type_ids)
        for layer in self.encoder:
            x = layer(x, attention_mask)
        return x, self.pooler(x)


class ErnieForSequenceClassification(Layer):
    def __init__(self, config: ErnieConfig, num_classes: int = 2):
        super().__init__()
        self.ernie = ErnieModel(config)
        self.dropout = Dropout(config.hidden_dropout)
        self.classifier = Linear(config.hidden_size, num_classes,
                                 weight_attr=_w(config))

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None, labels=None):
        _, pooled = self.ernie(input_ids, token_type_ids, position_ids,
                               attention_mask)
        logits = self.classifier(self.dropout(pooled))
        if labels is not None:
            return F.cross_entropy(logits, labels), logits
        return logits


class ErnieForPretraining(Layer):
    """MLM + NSP heads (ERNIE pretraining objective; MLM projection tied to
    the word embedding)."""

    def __init__(self, config: ErnieConfig):
        super().__init__()
        self.ernie = ErnieModel(config)
        h = config.hidden_size
        self.transform = Linear(h, h, weight_attr=_w(config))
        self.transform_norm = LayerNorm(h, epsilon=config.layer_norm_eps)
        self.nsp_head = Linear(h, 2, weight_attr=_w(config))

    def forward(self, input_ids, token_type_ids=None, masked_positions=None,
                labels=None, next_sentence_labels=None, **kw):
        seq, pooled = self.ernie(input_ids, token_type_ids)
        x = self.transform_norm(F.gelu(self.transform(seq)))
        mlm_logits = matmul(x, self.ernie.embeddings.word_embeddings.weight,
                            transpose_y=True)
        nsp_logits = self.nsp_head(pooled)
        if labels is not None:
            mlm_loss = F.cross_entropy(
                reshape(mlm_logits, [-1, mlm_logits.shape[-1]]),
                reshape(labels, [-1]), ignore_index=-100)
            loss = mlm_loss
            if next_sentence_labels is not None:
                loss = loss + F.cross_entropy(nsp_logits,
                                              next_sentence_labels)
            return loss, mlm_logits, nsp_logits
        return mlm_logits, nsp_logits
