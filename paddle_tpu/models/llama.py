"""LLaMA model family.

Architecture parity: the reference's auto-parallel llama end-to-end tests
(test/auto_parallel/hybrid_strategy/semi_auto_llama.py — dp/mp/pp configs
with acc-alignment oracles) — RMSNorm pre-norm, rotary position embeddings,
SwiGLU MLP, optional grouped-query attention (GQA). Attention runs through
``F.scaled_dot_product_attention`` (Pallas flash attention on TPU); RoPE is
the fused incubate op so XLA folds it into the attention prologue.

Tensor parallelism mirrors the GPT family: Column/RowParallelLinear +
VocabParallelEmbedding when a model-parallel group is active (Megatron
layout, reference mp_layers.py:47/:333/:540). The mp degree must divide
both num_heads and num_kv_heads (construction raises otherwise).
"""
from __future__ import annotations

from dataclasses import dataclass

from ..framework.param_attr import ParamAttr
from ..nn import Layer, functional as F
from ..nn.initializer import Normal
from ..nn.layer.common import Embedding
from ..nn.layer.container import LayerList
from ..nn.layer.norm import RMSNorm
from ..tensor.manipulation import reshape
from ..tensor.math import matmul
from ._tp import mp_degree as _mp_degree, tp_enabled as _tp_enabled


@dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int | None = None  # None = MHA; < num_heads = GQA
    max_seq_len: int = 2048
    rms_norm_eps: float = 1e-6
    initializer_range: float = 0.02
    rope_theta: float = 10000.0
    tie_word_embeddings: bool = False
    use_flash_attention: bool = True
    tensor_parallel: bool = False

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def kv_heads(self) -> int:
        return self.num_kv_heads or self.num_heads


LLAMA_CONFIGS: dict[str, LlamaConfig] = {
    "llama-tiny": LlamaConfig(vocab_size=1024, hidden_size=128,
                              intermediate_size=352, num_layers=2,
                              num_heads=4, num_kv_heads=2, max_seq_len=128),
    "llama-7b": LlamaConfig(),
    "llama-13b": LlamaConfig(hidden_size=5120, intermediate_size=13824,
                             num_layers=40, num_heads=40),
    "llama2-70b": LlamaConfig(hidden_size=8192, intermediate_size=28672,
                              num_layers=80, num_heads=64, num_kv_heads=8,
                              max_seq_len=4096),
}


def _w(config: LlamaConfig) -> ParamAttr:
    return ParamAttr(initializer=Normal(mean=0.0,
                                        std=config.initializer_range))


def _linear(config, in_f, out_f, kind):
    """kind: 'col' (shard output dim) | 'row' (shard input dim) | 'plain'.
    LLaMA projections carry no bias."""
    from ._tp import tp_linear

    return tp_linear(config, in_f, out_f, kind, _w(config), has_bias=False)


class LlamaAttention(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        h, hd = config.hidden_size, config.head_dim
        if _tp_enabled(config):
            ws = max(_mp_degree(), 1)
            if config.num_heads % ws != 0 or config.kv_heads % ws != 0:
                raise ValueError(
                    f"tensor parallel degree {ws} must divide num_heads "
                    f"{config.num_heads} and num_kv_heads {config.kv_heads} "
                    "(KV-head replication across the mp group is not "
                    "implemented — pick mp_degree | num_kv_heads)")
        self.q_proj = _linear(config, h, config.num_heads * hd, "col")
        self.k_proj = _linear(config, h, config.kv_heads * hd, "col")
        self.v_proj = _linear(config, h, config.kv_heads * hd, "col")
        self.o_proj = _linear(config, config.num_heads * hd, h, "row")

    def forward(self, x, position_ids=None):
        from ..incubate.nn.functional import fused_rotary_position_embedding

        cfg = self.config
        B, S, _ = x.shape
        # Global view: TP sharding lives on the WEIGHTS (Shard annotations);
        # activations keep their GLOBAL shapes — the head split is an XLA
        # partitioning decision, not a python-visible division. (The
        # divisibility check in __init__ guarantees the partitioner can
        # split heads evenly across the mp axis.)
        nh, nkv, hd = cfg.num_heads, cfg.kv_heads, cfg.head_dim
        q = reshape(self.q_proj(x), [B, S, nh, hd])
        k = reshape(self.k_proj(x), [B, S, nkv, hd])
        v = reshape(self.v_proj(x), [B, S, nkv, hd])
        q, k, _ = fused_rotary_position_embedding(
            q, k, None, position_ids=position_ids)
        if nkv < nh:  # GQA: repeat kv heads to match query heads
            rep = nh // nkv
            k = k.unsqueeze(3).expand([B, S, nkv, rep, hd]).reshape(
                [B, S, nh, hd])
            v = v.unsqueeze(3).expand([B, S, nkv, rep, hd]).reshape(
                [B, S, nh, hd])
        out = F.scaled_dot_product_attention(q, k, v, is_causal=True)
        return self.o_proj(reshape(out, [B, S, nh * hd]))


class LlamaMLP(Layer):
    """SwiGLU: down(silu(gate(x)) * up(x))."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        h, i = config.hidden_size, config.intermediate_size
        self.gate_proj = _linear(config, h, i, "col")
        self.up_proj = _linear(config, h, i, "col")
        self.down_proj = _linear(config, i, h, "row")

    def forward(self, x):
        return self.down_proj(F.silu(self.gate_proj(x)) * self.up_proj(x))


class LlamaDecoderLayer(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.input_layernorm = RMSNorm(config.hidden_size,
                                       epsilon=config.rms_norm_eps)
        self.self_attn = LlamaAttention(config)
        self.post_attention_layernorm = RMSNorm(config.hidden_size,
                                                epsilon=config.rms_norm_eps)
        self.mlp = LlamaMLP(config)

    def forward(self, x, position_ids=None):
        x = x + self.self_attn(self.input_layernorm(x), position_ids)
        return x + self.mlp(self.post_attention_layernorm(x))


class LlamaModel(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        if _tp_enabled(config):
            from ..distributed.fleet.meta_parallel.mp_layers import (
                VocabParallelEmbedding,
            )

            self.embed_tokens = VocabParallelEmbedding(
                config.vocab_size, config.hidden_size, weight_attr=_w(config))
        else:
            self.embed_tokens = Embedding(config.vocab_size,
                                          config.hidden_size,
                                          weight_attr=_w(config))
        self.layers = LayerList(
            [LlamaDecoderLayer(config) for _ in range(config.num_layers)])
        self.norm = RMSNorm(config.hidden_size, epsilon=config.rms_norm_eps)

    def forward(self, input_ids, position_ids=None):
        x = self.embed_tokens(input_ids)
        for layer in self.layers:
            x = layer(x, position_ids)
        return self.norm(x)


class LlamaForCausalLM(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.llama = LlamaModel(config)
        if config.tie_word_embeddings:
            self.lm_head = None
        else:
            self.lm_head = _linear(config, config.hidden_size,
                                   config.vocab_size, "plain")

    def forward(self, input_ids, position_ids=None, labels=None):
        hidden = self.llama(input_ids, position_ids)
        if self.lm_head is not None:
            logits = self.lm_head(hidden)
        else:
            logits = matmul(hidden, self.llama.embed_tokens.weight,
                            transpose_y=True)
        if labels is not None:
            loss = F.cross_entropy(
                reshape(logits, [-1, logits.shape[-1]]),
                reshape(labels, [-1]))
            return loss, logits
        return logits
