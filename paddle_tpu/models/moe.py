"""Mixture-of-Experts routing + expert FFN — the GPT MoE subsystem.

One functional core serves every consumer, so the serving step, the eager
oracle and the SPMD training block cannot drift apart:

- :func:`route_topk` — deterministic top-k softmax routing (iterative
  argmax + one-hot masking: ties break to the LOWEST expert index on
  every path, so greedy serving stays bit-reproducible);
- :func:`moe_capacity` / :func:`capacity_positions` — GShard capacity
  math: per-(token, choice) slot ranks in choice-major priority (all
  first choices queue before any second choice, the ``top2_gating``
  discipline), tokens past an expert's capacity DROP — their FFN
  contribution is exactly zero so the residual carries them through;
- :func:`moe_ffn` — the grouped-GEMM formulation (sort token-choice
  pairs by expert, one ragged ``ops/pallas/grouped_matmul`` per FFN
  matmul, combine by renormalized gates). This is THE spelling both the
  eager :class:`GPTMoE` module and the serving blocks call — greedy
  serving == full-forward oracle is structural, not a numerical
  accident;
- :func:`topk_dispatch_combine` — the einsum (dispatch/combine mask)
  formulation the SPMD training block uses: dense ``[N, E, C]`` masks
  lower cleanly under GSPMD with experts sharded over the ``ep`` axis
  (``gpt_spmd._moe_block``), generalizing the orphaned
  ``meta_parallel/moe_layer.py`` top-1/top-2 gates to any k (that module
  now re-exports these primitives);
- aux load-balance loss: ``E * sum(frac_tokens_per_expert *
  mean_router_prob_per_expert)`` over the FIRST choice (GShard eq. 13 /
  Switch eq. 4).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.param_attr import ParamAttr
from ..nn import Layer
from ..nn.initializer import Normal


def moe_capacity(n_tokens: int, num_experts: int, top_k: int,
                 capacity_factor: float) -> int:
    """Per-expert slot budget (static): the ``moe_layer`` formula
    generalized to k — ``max(int(factor * n / E) * k, 4)``. A factor >=
    ``num_experts`` can never drop a token (an expert sees at most ``n``
    of the ``n * k`` choices)."""
    return max(int(float(capacity_factor) * int(n_tokens)
                   / int(num_experts)) * int(top_k), 4)


def route_topk(logits, top_k: int):
    """Deterministic top-k routing over router ``logits [N, E]``.

    Returns ``(gates [N, k] fp32, idx [N, k] int32, probs [N, E] fp32,
    masks)`` — gates renormalized over the k selections (GShard denom),
    ``masks`` the per-choice one-hot ``[N, E]`` list. ``jnp.argmax``
    breaks ties to the lowest index, and the iterative masking keeps the
    k experts distinct."""
    n, e = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    p = probs
    idxs, raw, masks = [], [], []
    for _ in range(int(top_k)):
        i = jnp.argmax(p, axis=-1)
        m = jax.nn.one_hot(i, e, dtype=jnp.float32)
        idxs.append(i.astype(jnp.int32))
        raw.append((p * m).sum(axis=-1))
        masks.append(m)
        p = p * (1.0 - m)
    gates = jnp.stack(raw, axis=1)                       # [N, k]
    gates = gates / jnp.maximum(gates.sum(axis=1, keepdims=True), 1e-9)
    return gates, jnp.stack(idxs, axis=1), probs, masks


def load_balance_aux(probs, mask1, valid=None):
    """GShard aux loss: ``E * sum(frac_per_expert * mean_prob_per_expert)``
    over FIRST choices; ``valid [N]`` excludes padding rows."""
    e = probs.shape[-1]
    if valid is None:
        frac = mask1.mean(axis=0)
        pmean = probs.mean(axis=0)
    else:
        vw = valid.astype(jnp.float32)[:, None]
        denom = jnp.maximum(vw.sum(), 1.0)
        frac = (mask1 * vw).sum(axis=0) / denom
        pmean = (probs * vw).sum(axis=0) / denom
    return jnp.sum(frac * pmean) * e


def capacity_positions(masks, capacity: int, valid=None):
    """Per-(token, choice) slot index in the chosen expert's capacity
    buffer, choice-major priority (``top2_gating``'s offset discipline
    generalized): returns ``pos [N, k]`` — ``pos >= capacity`` means the
    choice DROPS. ``valid`` rows never consume a slot (pos -1)."""
    e = masks[0].shape[-1]
    offset = jnp.zeros((e,), jnp.float32)
    poss = []
    for m in masks:
        mv = m if valid is None else m * valid.astype(jnp.float32)[:, None]
        ranks = jnp.cumsum(mv, axis=0) + offset[None, :]
        poss.append((ranks * mv).sum(axis=-1) - 1.0)
        offset = offset + mv.sum(axis=0)
    return jnp.stack(poss, axis=1)                       # [N, k] float


def _grouped_mm(xs, w, offsets, use_kernel):
    """fp stack or quantized ``{"q", "s"}`` dict through the ragged
    grouped GEMM (the ``_srv_mm`` convention per expert stack)."""
    from ..ops.pallas.grouped_matmul import grouped_matmul

    if isinstance(w, dict):
        return grouped_matmul(xs, w["q"], offsets, scales=w["s"],
                              use_kernel=use_kernel)
    return grouped_matmul(xs, w, offsets, use_kernel=use_kernel)


def _expert_bias(b, eids):
    """Per-row bias gather from an ``[E, F]`` stack."""
    return jnp.take(b, eids, axis=0)


def moe_ffn(x, gate_w, w1, b1, w2, b2, *, top_k: int,
            capacity_factor: float, use_kernel=None, valid=None,
            with_stats: bool = False):
    """The MoE FFN over 2D tokens ``x [N, d]``.

    gate_w ``[d, E]``; w1 ``[E, d, f]`` / w2 ``[E, f, d]`` (fp stacks or
    quantized ``{"q", "s"}`` dicts — inference/quantize.py layout); b1
    ``[E, f]``; b2 ``[E, d]``. ``valid [N]`` masks padding rows (serving's
    packed stream): invalid rows route nowhere — zero gates, no capacity
    slot, zero output. Dropped token-choice pairs (capacity overflow)
    keep their expert assignment in the grouped layout but combine with
    gate 0 — the token rides the residual.

    Returns ``(out [N, d], aux_loss)`` — plus a stats dict (``load [E]``
    kept-pair fraction per expert, ``drop_rate``) when ``with_stats``.
    """
    n, d = x.shape
    e = gate_w.shape[-1]
    k = int(top_k)
    logits = x.astype(jnp.float32) @ gate_w.astype(jnp.float32)
    gates, idx, probs, masks = route_topk(logits, k)
    aux = load_balance_aux(probs, masks[0], valid=valid)
    cap = moe_capacity(n, e, k, capacity_factor)
    pos = capacity_positions(masks, cap, valid=valid)
    keep = (pos >= 0.0) & (pos < cap)                     # [N, k]
    if valid is not None:
        keep = keep & valid[:, None]
    gates = gates * keep.astype(gates.dtype)

    # token-choice pairs sorted by expert (stable: deterministic intra-
    # expert order = token-major arrival) — the ragged grouped layout
    pair_tok = jnp.repeat(jnp.arange(n, dtype=jnp.int32), k)  # [N*k]
    eid = idx.reshape(-1)                                     # [N*k]
    order = jnp.argsort(eid, stable=True).astype(jnp.int32)
    tok_sorted = pair_tok[order]
    eid_sorted = eid[order]
    counts = jnp.bincount(eid, length=e)
    offsets = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32),
         jnp.cumsum(counts).astype(jnp.int32)])

    xs = jnp.take(x, tok_sorted, axis=0)                      # [N*k, d]
    h = _grouped_mm(xs, w1, offsets, use_kernel)
    h = jax.nn.gelu(h + _expert_bias(b1, eid_sorted).astype(h.dtype),
                    approximate=True)
    y = (_grouped_mm(h.astype(x.dtype), w2, offsets, use_kernel)
         + _expert_bias(b2, eid_sorted).astype(x.dtype))
    g_sorted = gates.reshape(-1)[order].astype(jnp.float32)
    out = jnp.zeros((n, d), jnp.float32).at[tok_sorted].add(
        y.astype(jnp.float32) * g_sorted[:, None])
    out = out.astype(x.dtype)
    if not with_stats:
        return out, aux
    kept = keep.astype(jnp.float32)
    n_pairs = (jnp.maximum(valid.astype(jnp.float32).sum(), 1.0) * k
               if valid is not None else jnp.float32(n * k))
    load = jnp.zeros((e,), jnp.float32).at[eid].add(kept.reshape(-1))
    stats = {
        "load": load / jnp.maximum(load.sum(), 1.0),
        "drop_rate": 1.0 - jnp.minimum(kept.sum() / n_pairs, 1.0),
        "capacity": jnp.float32(cap),
    }
    return out, aux, stats


# ---------------------------------------------------------------------------
# einsum (dispatch/combine) formulation — the SPMD training spelling
# ---------------------------------------------------------------------------


def _combine_one(gate, mask, pos, capacity: int):
    keep = (pos >= 0) & (pos < capacity)
    mask = mask * keep[:, None].astype(mask.dtype)
    slots = jnp.clip(pos, 0, capacity - 1).astype(jnp.int32)
    oh = jax.nn.one_hot(slots, capacity, dtype=jnp.float32) * keep[:, None]
    return (gate * keep)[:, None, None] * mask[:, :, None] * oh[:, None, :]


def topk_dispatch_combine(logits, capacity: int, top_k: int):
    """GShard dense-mask gating generalized to any k: returns
    ``(combine [N, E, C], dispatch [N, E, C], aux_loss)``. ``k == 1``
    reproduces ``top1_gating`` (Switch), ``k == 2`` reproduces
    ``top2_gating`` — same argmax tie-breaks, same choice-major slot
    priority, same renormalized gates as :func:`moe_ffn`, so the einsum
    and grouped formulations compute the SAME function."""
    gates, _idx, probs, masks = route_topk(logits, top_k)
    aux = load_balance_aux(probs, masks[0])
    pos = capacity_positions(masks, capacity)
    combine = jnp.zeros(
        (logits.shape[0], logits.shape[1], int(capacity)), jnp.float32)
    for j, m in enumerate(masks):
        combine = combine + _combine_one(gates[:, j], m, pos[:, j],
                                         int(capacity))
    dispatch = (combine > 0).astype(logits.dtype)
    return combine, dispatch, aux


def moe_ffn_einsum(x, gate_w, w1, b1, w2, b2, *, top_k: int,
                   capacity_factor: float):
    """Capacity-dense einsum MoE (the GShard global_scatter/global_gather
    spelling): the training-path twin of :func:`moe_ffn`, and the parity
    oracle for ``moe_layer.MoELayer``. Returns ``(out [N, d], aux)``."""
    n = x.shape[0]
    e = gate_w.shape[-1]
    cap = moe_capacity(n, e, top_k, capacity_factor)
    logits = x.astype(jnp.float32) @ gate_w.astype(jnp.float32)
    combine, dispatch, aux = topk_dispatch_combine(logits, cap, top_k)
    expert_in = jnp.einsum("nec,nd->ecd", dispatch.astype(x.dtype), x)
    h = jax.nn.gelu(jnp.einsum("ecd,edh->ech", expert_in, w1)
                    + b1[:, None, :], approximate=True)
    expert_out = jnp.einsum("ech,ehd->ecd", h, w2) + b2[:, None, :]
    out = jnp.einsum("nec,ecd->nd", combine.astype(x.dtype), expert_out)
    return out, aux


def active_params_frac(config) -> float:
    """Analytic fraction of per-layer decoder weights a token actually
    streams under top-k routing (the bench's ``active_params_frac``):
    attention + router always stream, expert FFNs stream k of E."""
    e = int(getattr(config, "moe_experts", 0) or 0)
    if not e:
        return 1.0
    h, f = config.hidden_size, config.ffn_size
    k = int(config.moe_top_k)
    attn = 4 * h * h + 4 * h
    gate = h * e
    expert = 2 * h * f + h + f
    total = attn + gate + e * expert
    active = attn + gate + min(k, e) * expert
    return float(active) / float(total)


# ---------------------------------------------------------------------------
# eager module (GPTDecoderLayer's MLP when config.moe_experts > 0)
# ---------------------------------------------------------------------------


class GPTMoE(Layer):
    """Eager MoE FFN block — the GPTMLP drop-in for MoE configs.

    Expert weights are ONE stacked parameter per role (``w1 [E, h, f]``
    ...) so serving extraction stacks them ``[L, E, ...]`` exactly like
    the dense keys. Forward calls the SAME :func:`moe_ffn` the serving
    blocks run — full-forward oracle == serving step by construction.
    ``aux_loss`` and host-readable ``router_stats`` refresh per call
    (the bench's routing report reads them)."""

    def __init__(self, config):
        super().__init__()
        self.config = config
        h, f, e = config.hidden_size, config.ffn_size, config.moe_experts
        attr = ParamAttr(initializer=Normal(
            mean=0.0, std=config.initializer_range))
        self.gate_weight = self.create_parameter([h, e], attr=attr)
        self.w1 = self.create_parameter([e, h, f], attr=attr)
        self.b1 = self.create_parameter([e, f], is_bias=True)
        self.w2 = self.create_parameter([e, f, h], attr=attr)
        self.b2 = self.create_parameter([e, h], is_bias=True)
        self.aux_loss = None
        self.router_stats = None

    def forward(self, x):
        from ..autograd.engine import apply_op

        cfg = self.config

        def pure(xv, gw, w1, b1, w2, b2):
            tokens = xv.reshape(-1, xv.shape[-1])
            out, aux, stats = moe_ffn(
                tokens, gw, w1, b1, w2, b2,
                top_k=cfg.moe_top_k,
                capacity_factor=cfg.moe_capacity_factor,
                with_stats=True)
            return (out.reshape(xv.shape), aux, stats["load"],
                    stats["drop_rate"])

        out, aux, load, drop = apply_op(
            "moe_layer", pure, x, self.gate_weight, self.w1,
            self.b1, self.w2, self.b2)
        self.aux_loss = aux
        self.router_stats = {"load": load, "drop_rate": drop}
        return out
