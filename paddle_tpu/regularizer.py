"""paddle.regularizer parity (python/paddle/regularizer.py)."""
from __future__ import annotations


class WeightDecayRegularizer:
    pass


class L2Decay(WeightDecayRegularizer):
    def __init__(self, coeff: float = 0.0):
        self._coeff = float(coeff)

    def __float__(self):
        return self._coeff


class L1Decay(WeightDecayRegularizer):
    def __init__(self, coeff: float = 0.0):
        self._coeff = float(coeff)

    def __float__(self):
        return self._coeff
