"""paddle.geometric parity: graph message passing + segment ops.

Reference: python/paddle/geometric (send_u_recv, send_ue_recv,
send_uv, segment_sum/mean/max/min, reindex_graph, sample_neighbors).
TPU-native: message passing is gather + segment-reduce — XLA scatter
kernels; the segment ops re-export the incubate implementations.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..autograd.engine import apply_op
from ..incubate import segment_max, segment_mean, segment_min, segment_sum
from ..tensor.tensor import Tensor

_REDUCERS = {
    "sum": jax.ops.segment_sum,
    "mean": None,  # handled specially
    "max": jax.ops.segment_max,
    "min": jax.ops.segment_min,
}


def _segment_reduce(vals, dst, num, pool_type):
    if pool_type == "mean":
        s = jax.ops.segment_sum(vals, dst, num_segments=num)
        cnt = jax.ops.segment_sum(jnp.ones((vals.shape[0],) + (1,) * (vals.ndim - 1),
                                           vals.dtype), dst, num_segments=num)
        return s / jnp.maximum(cnt, 1)
    red = _REDUCERS[pool_type]
    out = red(vals, dst, num_segments=num)
    if pool_type in ("max", "min"):
        # empty segments produce +-inf; the reference zero-fills them
        out = jnp.where(jnp.isfinite(out), out, 0.0)
    return out


def send_u_recv(x: Tensor, src_index: Tensor, dst_index: Tensor,
                reduce_op: str = "sum", out_size=None, name=None):
    """Gather x[src] along edges, reduce at dst (reference:
    geometric/message_passing/send_recv.py send_u_recv)."""
    num = int(out_size) if out_size is not None else int(x.shape[0])

    def fn(xd, src, dst):
        return _segment_reduce(xd[src], dst, num, reduce_op)

    return apply_op("send_u_recv", fn, x, src_index, dst_index)


def send_ue_recv(x: Tensor, y: Tensor, src_index: Tensor, dst_index: Tensor,
                 message_op: str = "add", reduce_op: str = "sum",
                 out_size=None, name=None):
    """Combine node features x[src] with edge features y, reduce at dst."""
    num = int(out_size) if out_size is not None else int(x.shape[0])
    combine = {
        "add": jnp.add, "sub": jnp.subtract,
        "mul": jnp.multiply, "div": jnp.divide,
    }[message_op]

    def fn(xd, yd, src, dst):
        return _segment_reduce(combine(xd[src], yd), dst, num, reduce_op)

    return apply_op("send_ue_recv", fn, x, y, src_index, dst_index)


def send_uv(x: Tensor, y: Tensor, src_index: Tensor, dst_index: Tensor,
            message_op: str = "add", name=None):
    """Per-edge message x[src] op y[dst] (reference send_uv)."""
    combine = {
        "add": jnp.add, "sub": jnp.subtract,
        "mul": jnp.multiply, "div": jnp.divide,
    }[message_op]

    def fn(xd, yd, src, dst):
        return combine(xd[src], yd[dst])

    return apply_op("send_uv", fn, x, y, src_index, dst_index)


def weighted_sample_neighbors(row, colptr, edge_weight, input_nodes,
                              sample_size=-1, eids=None, return_eids=False,
                              name=None):
    """Weight-biased neighbor sampling from a CSC graph (reference:
    geometric/sampling/neighbor_sample.py weighted_sample_neighbors):
    sample up to ``sample_size`` in-neighbors of each input node WITHOUT
    replacement, picking each neighbor with probability proportional to
    its ``edge_weight`` (A-ExpJ reservoir in the reference kernel — the
    same weighted-without-replacement distribution drawn here on the
    host). Returns (neighbors, count[, eids]).

    Host op like ``graph_sample_neighbors`` (data-dependent output size),
    seeded from the framework generator so ``paddle.seed`` replays the
    samples; both ride the shared CSC sampler in ``incubate.graph_ops``.
    """
    from ..incubate.graph_ops import sample_csc_neighbors

    neighbors, count, picked = sample_csc_neighbors(
        row, colptr, input_nodes, sample_size=sample_size, eids=eids,
        return_eids=return_eids, edge_weight=edge_weight)
    if return_eids:
        return neighbors, count, picked
    return neighbors, count


__all__ = ["send_u_recv", "send_ue_recv", "send_uv", "segment_sum",
           "segment_mean", "segment_max", "segment_min",
           "weighted_sample_neighbors"]
