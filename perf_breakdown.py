"""Per-phase attribution of the flagship GPT train step (round-4 verdict #1).

Differential timing on the real chip: the full fused K-step scan is timed
against variants with one phase removed (attention branch, MLP branch,
softmax-CE math) and against structural splits (forward-only,
forward+backward without the update). Phase cost = full − ablated. A pure
ideal-matmul scan of the model's exact GEMM set gives the attainable-MFU
ceiling for the same shapes — the roofline the model step is chasing
(answers "where do the other ~44% go" and makes the GPT-125M h=768
ceiling a measured number, not a sentence).

Methodology notes: same K-scan + replay-original-inputs discipline as
bench.py (avoids the axon tunnel's donation and relayout pathologies);
ablated variants change compiled memory behavior minimally (the "ce"
ablation keeps the chunked-remat structure and head matmuls).

Usage: python perf_breakdown.py [--model 760m|125m] [--json out.json]
"""
from __future__ import annotations

import argparse
import json
import time

import numpy as np

from bench import _chip_peak  # shared chip table / methodology


def _step_time(cfg, mesh, batch, seq, K, mode):
    """Seconds/step for one variant of the train step.

    mode: 'full' (fwd+bwd+update), 'grad' (fwd+bwd), 'fwd' (loss only).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    from paddle_tpu.models import gpt_spmd

    lr, momentum = 1e-4, 0.9
    params = gpt_spmd.init_params(cfg, mesh, dtype=jnp.bfloat16)
    mom = jax.tree.map(jnp.zeros_like, params)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)), jnp.int32)
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)),
                         jnp.int32)

    def one_full(p, m, ids_, labels_):
        loss, grads = jax.value_and_grad(gpt_spmd.loss_fn)(
            p, ids_, labels_, cfg, mesh, 1)
        m2 = jax.tree.map(lambda a, g: momentum * a + g.astype(a.dtype),
                          m, grads)
        p2 = jax.tree.map(lambda a, b: a - lr * b, p, m2)
        return p2, m2, loss

    def one_grad(p, ids_, labels_):
        return jax.value_and_grad(gpt_spmd.loss_fn)(p, ids_, labels_, cfg,
                                                    mesh, 1)

    def one_fwd(p, ids_, labels_):
        return gpt_spmd.loss_fn(p, ids_, labels_, cfg, mesh, 1)

    def many_mode(params, mom, ids, labels):
        def body(carry, _):
            p, m, salt = carry
            if mode != "full":
                # defeat loop-invariant hoisting: the params must depend on
                # the previous iteration's loss or XLA computes the (fixed-
                # input) body ONCE outside the scan
                p = dict(p)
                p["lnf_g"] = p["lnf_g"] + (salt * 1e-30).astype(
                    p["lnf_g"].dtype)
            if mode == "full":
                p2, m2, loss = one_full(p, m, ids, labels)
                return (p2, m2, loss.astype(jnp.float32)), loss
            if mode == "grad":
                loss, grads = one_grad(p, ids, labels)
                # consume grads at a non-zero weight so XLA cannot DCE the
                # backward (literal *0.0 would be constant-folded away)
                gsum = sum(jnp.sum(jnp.abs(g).astype(jnp.float32))
                           for g in jax.tree.leaves(grads))
                loss = loss + gsum * 1e-30
                return (p, m, loss.astype(jnp.float32)), loss
            loss = one_fwd(p, ids, labels)
            return (p, m, loss.astype(jnp.float32)), loss

        salt0 = jnp.zeros((), jnp.float32)
        _, losses = lax.scan(body, (params, mom, salt0), None, length=K)
        return losses

    with jax.set_mesh(mesh):
        jit = jax.jit(many_mode)
        losses = jit(params, mom, ids, labels)
        np.asarray(losses)
        t0 = time.perf_counter()
        losses = jit(params, mom, ids, labels)
        np.asarray(losses)
        return (time.perf_counter() - t0) / K


def matmul_roofline(cfg, batch, seq, K):
    """Seconds/step for the model's exact GEMM set alone, fwd+bwd shapes:
    per layer fwd (qkv, proj, mlp-in, mlp-out + attention einsums) plus the
    2x backward passes, plus 3x head matmul (fwd + bwd + remat-CE extra
    pass). Everything bf16 on the MXU, no LN/softmax/residuals — the
    attainable ceiling for this model's shapes."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    h, L = cfg.hidden_size, cfg.num_layers
    nh, hd = cfg.num_heads, cfg.head_dim
    v = cfg.vocab_size
    T = batch * seq
    rng = np.random.RandomState(0)

    def t(*shape):
        return jnp.asarray(rng.randn(*shape), jnp.bfloat16)

    x = t(T, h)
    wqkv, wo = t(h, 3 * h), t(h, h)
    w1, w2 = t(h, 4 * h), t(4 * h, h)
    emb = t(v, h)
    q = t(batch, nh, seq, hd)

    def gemms(x, q, wqkv, wo, w1, w2, emb, salt):
        # Every repetition is perturbed by the running accumulator so XLA
        # cannot CSE the 3xL identical GEMM sets into one, and every output
        # is fully consumed (a partial slice would let XLA narrow the GEMM).
        acc = salt
        with jax.default_matmul_precision("default"):
            for _ in range(3):  # fwd + 2 bwd passes (dgrad + wgrad)
                for _l in range(L):
                    a = x @ wqkv
                    s_ = jnp.einsum("bnqd,bnkd->bnqk", q, q)
                    o = jnp.einsum("bnqk,bnkd->bnqd", s_, q)
                    b_ = x @ wo
                    c = x @ w1
                    d = c @ w2
                    acc = acc + (jnp.sum(a) + jnp.sum(o) + jnp.sum(b_)
                                 + jnp.sum(d)).astype(jnp.float32) * 1e-30
                    x = x + (acc * 1e-20).astype(x.dtype)
                    q = q + (acc * 1e-20).astype(q.dtype)
                lg = x @ emb.T
                acc = acc + jnp.sum(lg).astype(jnp.float32) * 1e-30
        return acc

    def many(x, q, wqkv, wo, w1, w2, emb):
        def body(carry, _):
            return gemms(x, q, wqkv, wo, w1, w2, emb, carry), None

        out, _ = lax.scan(body, jnp.zeros((), jnp.float32), None, length=K)
        return out

    jit = jax.jit(many)
    out = jit(x, q, wqkv, wo, w1, w2, emb)
    np.asarray(out)
    t0 = time.perf_counter()
    np.asarray(jit(x, q, wqkv, wo, w1, w2, emb))
    per_step = (time.perf_counter() - t0) / K

    # FLOPs of that GEMM set
    per_layer = (2 * T * h * 3 * h + 2 * batch * nh * seq * seq * hd * 2
                 + 2 * T * h * h + 2 * T * h * 4 * h + 2 * T * 4 * h * h)
    total = 3 * (L * per_layer + 2 * T * h * v)
    return per_step, total


def attention_ab(batch, nh, seq, hd, K=16):
    """Isolated fwd+bwd A/B: Pallas flash kernel vs XLA fused attention at
    one (batch, heads, seq, head_dim) shape, bf16, causal. Returns ms/step
    for each — the direct evidence for where the flash routing threshold
    belongs at this shape."""
    import math
    import jax
    import jax.numpy as jnp
    from jax import lax

    from paddle_tpu.ops.pallas.flash_attention import flash_attention

    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(batch, seq, nh, hd), jnp.bfloat16)
    k = jnp.asarray(rng.randn(batch, seq, nh, hd), jnp.bfloat16)
    v = jnp.asarray(rng.randn(batch, seq, nh, hd), jnp.bfloat16)

    def xla_attn(q, k, v):
        qt, kt, vt = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
        s = jnp.einsum("bhqd,bhkd->bhqk", qt, kt,
                       preferred_element_type=jnp.float32)
        s = s / math.sqrt(hd)
        s = jnp.where(jnp.tril(jnp.ones((seq, seq), bool)), s, -1e30)
        p = jax.nn.softmax(s, axis=-1).astype(vt.dtype)
        o = jnp.einsum("bhqk,bhkd->bhqd", p, vt)
        return o.transpose(0, 2, 1, 3)

    def run(fn):
        # grad wrt ALL of (q, k, v): XLA would DCE the dk/dv einsums of the
        # reference attention otherwise, while the fused Pallas backward
        # always computes them — a q-only grad would bias the A/B
        def loss(q, k, v):
            return jnp.sum(fn(q, k, v).astype(jnp.float32)) * 1e-30

        def many(q):
            def body(carry, _):
                gq, gk, gv = jax.grad(loss, argnums=(0, 1, 2))(
                    q + carry.astype(q.dtype), k, v)
                s = (jnp.sum(gq) + jnp.sum(gk)
                     + jnp.sum(gv)).astype(jnp.float32)
                return carry + s * 1e-30, None

            out, _ = lax.scan(body, jnp.zeros((), jnp.float32), None,
                              length=K)
            return out

        with jax.default_matmul_precision("default"):
            jit = jax.jit(many)
            np.asarray(jit(q))
            t0 = time.perf_counter()
            np.asarray(jit(q))
            return (time.perf_counter() - t0) / K * 1e3

    return {
        "shape": f"b{batch} h{nh} s{seq} d{hd} bf16 causal",
        "flash_ms": round(run(lambda q, k, v: flash_attention(
            q, k, v, causal=True)), 3),
        "xla_ms": round(run(xla_attn), 3),
    }


def main():
    import jax

    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="760m", choices=["760m", "125m"])
    ap.add_argument("--json", default=None)
    ap.add_argument("-K", type=int, default=8)
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (skip the TPU tunnel)")
    ap.add_argument("--attn", action="store_true",
                    help="isolated flash-vs-XLA attention A/B only")
    args = ap.parse_args()
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    if args.attn:
        on_tpu = jax.default_backend() == "tpu"
        shapes = ((12, 128), (12, 64)) if on_tpu else ((4, 64),)
        seqs = (512, 1024, 2048) if on_tpu else (256,)
        b = 8 if on_tpu else 2
        for nh, hd in shapes:
            for seq in seqs:
                print(json.dumps(attention_ab(b, nh, seq, hd,
                                              K=16 if on_tpu else 2)))
        return

    from paddle_tpu.models import gpt_spmd
    from paddle_tpu.models.gpt import GPTConfig

    on_tpu = jax.default_backend() == "tpu"
    if args.model == "760m":
        base = dict(hidden_size=1536, num_layers=24, num_heads=12,
                    recompute=True)
        batch, seq = 8, 1024
    else:
        base = dict(hidden_size=768, num_layers=12, num_heads=12,
                    recompute=False)
        batch, seq = 8, 1024
    if not on_tpu:
        batch, seq = 2, 256
        args.K = 2
    K = args.K
    mesh = gpt_spmd.make_mesh(1)

    def cfg_with(**kw):
        return GPTConfig(vocab_size=50304, max_seq_len=seq, **{**base, **kw})

    cfg = cfg_with()
    t_full = _step_time(cfg, mesh, batch, seq, K, "full")
    t_grad = _step_time(cfg, mesh, batch, seq, K, "grad")
    t_fwd = _step_time(cfg, mesh, batch, seq, K, "fwd")
    t_noattn = _step_time(cfg_with(ablate=("attn",)), mesh, batch, seq, K,
                          "full")
    t_nomlp = _step_time(cfg_with(ablate=("mlp",)), mesh, batch, seq, K,
                         "full")
    t_noce = _step_time(cfg_with(ablate=("ce",)), mesh, batch, seq, K,
                        "full")
    mm_time, mm_flops = matmul_roofline(cfg, batch, seq, K)

    chip, peak = _chip_peak(jax, on_tpu)
    n_params = cfg.num_params()
    tok = batch * seq
    flops_per_token = 6 * n_params + 6 * cfg.num_layers * cfg.hidden_size * seq
    step_flops = flops_per_token * tok

    phases = {
        "full_step_ms": t_full * 1e3,
        "forward_ms": t_fwd * 1e3,
        "backward_ms": (t_grad - t_fwd) * 1e3,
        "optimizer_update_ms": (t_full - t_grad) * 1e3,
        "attention_total_ms": (t_full - t_noattn) * 1e3,
        "mlp_total_ms": (t_full - t_nomlp) * 1e3,
        "softmax_ce_math_ms": (t_full - t_noce) * 1e3,
        "ideal_gemm_set_ms": mm_time * 1e3,
    }
    result = {
        "model": args.model,
        "chip": chip,
        "batch": batch,
        "seq": seq,
        "K": K,
        "phases_ms": {k: round(v, 2) for k, v in phases.items()},
        "mfu_full_step": round(step_flops / t_full / peak, 4),
        "mfu_ideal_gemms": round(mm_flops / mm_time / peak, 4),
        "tokens_per_s": round(tok / t_full, 1),
    }
    text = json.dumps(result, indent=1)
    if args.json:
        with open(args.json, "w") as f:
            f.write(text + "\n")
    print(text)


if __name__ == "__main__":
    main()
