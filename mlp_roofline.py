"""MLP-side bandwidth attribution (round-5 verdict #5).

Times ONE decoder layer's MLP branch (LN + h->4h GEMM + gelu + 4h->h GEMM +
residual) fwd+bwd at the flagship shape against (a) the same two GEMMs alone
and (b) the branch with remat (the training configuration), then sets the
measured elementwise overhead against its minimum HBM traffic at the chip's
~819 GB/s — the roofline argument for whether a fused LN/residual Pallas
kernel has anything left to win.

Usage: python mlp_roofline.py [--json out.json]
"""
from __future__ import annotations

import argparse
import json
import time

import numpy as np

HBM_GBPS = {"TPU v5 lite": 819e9, "TPU v5p": 2765e9, "TPU v4": 1228e9}


def main():
    import jax
    import jax.numpy as jnp
    from jax import lax

    from bench import _chip_peak

    ap = argparse.ArgumentParser()
    ap.add_argument("--json", default=None)
    ap.add_argument("-K", type=int, default=32)
    args = ap.parse_args()

    on_tpu = jax.default_backend() == "tpu"
    B, S, H = (8, 1024, 1536) if on_tpu else (2, 128, 256)
    K = args.K if on_tpu else 2
    eps = 1e-5
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(B, S, H), jnp.bfloat16)
    g = jnp.asarray(rng.randn(H), jnp.bfloat16)
    b = jnp.asarray(rng.randn(H), jnp.bfloat16)
    w1 = jnp.asarray(rng.randn(H, 4 * H) * 0.02, jnp.bfloat16)
    w2 = jnp.asarray(rng.randn(4 * H, H) * 0.02, jnp.bfloat16)

    def ln(x, g, b):
        mu = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.var(x, axis=-1, keepdims=True)
        return (x - mu) * lax.rsqrt(var + eps) * g + b

    def mlp(x, g, b, w1, w2):
        y = ln(x, g, b)
        y = jax.nn.gelu(y @ w1, approximate=True)
        return x + y @ w2

    def gemms_only(x, w1, w2):
        # same GEMM content as the branch (fwd 2, bwd 4), no LN/gelu/residual
        return (x @ w1) @ w2

    def timed(fn, *inp):
        def loss(*a):
            return jnp.sum(fn(*a).astype(jnp.float32)) * 1e-30

        def many(x0):
            def body(c, _):
                grads = jax.grad(loss, argnums=tuple(range(len(inp))))(
                    x0 + c.astype(x0.dtype), *inp[1:])
                s = sum(jnp.sum(gr).astype(jnp.float32) for gr in grads)
                return c + s * 1e-30, None

            out, _ = lax.scan(body, jnp.zeros((), jnp.float32), None, length=K)
            return out

        with jax.default_matmul_precision("default"):
            f = jax.jit(many)
            np.asarray(f(inp[0]))
            t0 = time.perf_counter()
            np.asarray(f(inp[0]))
            return (time.perf_counter() - t0) / K * 1e3  # ms

    t_mlp = timed(mlp, x, g, b, w1, w2)
    t_mlp_remat = timed(jax.checkpoint(
        mlp, policy=jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims
    ), x, g, b, w1, w2)
    t_gemm = timed(gemms_only, x, w1, w2)

    # Minimum HBM traffic of the NON-GEMM work, assuming perfect epilogue
    # fusion (gelu/residual ride the GEMM tiles): fwd LN read+write 2*BSH,
    # bwd LN read dy + x + write dx ~ 3*BSH, remat re-forward LN another
    # 2*BSH; gelu bwd reads the saved w1-output 4*BSH... counted at bf16.
    bsh = B * S * H * 2  # bytes
    min_bytes = (2 + 3 + 2) * bsh + 2 * 4 * bsh  # LN legs + gelu-grad read/write
    chip, _ = _chip_peak(jax, on_tpu)
    bw = HBM_GBPS.get(chip, 819e9)
    roofline_ms = min_bytes / bw * 1e3

    out = {
        "shape": f"B{B} S{S} H{H} bf16, one layer, fwd+bwd",
        "mlp_branch_ms": round(t_mlp, 3),
        "mlp_branch_remat_ms": round(t_mlp_remat, 3),
        "gemms_only_ms": round(t_gemm, 3),
        "elementwise_overhead_ms": round(t_mlp_remat - t_gemm, 3),
        "min_hbm_bytes_nongemm": min_bytes,
        "roofline_ms_at_bw": round(roofline_ms, 3),
        "chip": chip,
        "verdict": None,
    }
    ratio = (t_mlp_remat - t_gemm) / max(roofline_ms, 1e-9)
    out["verdict"] = (
        f"measured elementwise overhead is {ratio:.2f}x its HBM roofline — "
        + ("XLA fusion is near-optimal; a Pallas LN kernel has <~"
           f"{max(0.0, (t_mlp_remat - t_gemm) - roofline_ms):.1f} ms/layer to win"
           if ratio < 1.6 else
           "fusion gap: a fused LN/residual Pallas kernel is worth building"))
    print(json.dumps(out, indent=1))
    if args.json:
        with open(args.json, "w") as f:
            f.write(json.dumps(out, indent=1) + "\n")


if __name__ == "__main__":
    main()
