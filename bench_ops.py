"""Per-op performance regression harness.

Reference: tools/ci_op_benchmark.sh + tools/check_op_benchmark_result.py —
the reference gates op perf in CI by comparing per-op timings against a
stored baseline. This sweeps the hottest registry ops at fixed
transformer-ish shapes through the REAL dispatch path (apply_op, eager
cache at its default state) and emits one JSON object:

    {"device": "...", "platform": "tpu|cpu", "ops": {name: {"us": median,
     "shape": "..."}}}

Usage:
    python bench_ops.py                     # print JSON to stdout
    python bench_ops.py --out BENCH_OPS_r04.json
    python bench_ops.py --iters 50

The gate test (tests/test_bench_ops.py, opt-in via -m bench) compares a
fresh sweep against the committed file for the SAME platform and fails on
>TOL regressions.
"""
from __future__ import annotations

import argparse
import json
import sys
import time
from statistics import median as _median


def build_cases():
    """(name, thunk) pairs. Shapes: decoder-block-ish at b=8, s=512,
    h=1024 — big enough that the kernel dominates on TPU, small enough
    that a CPU sweep finishes in ~a minute."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.nn import functional as F

    rng = np.random.RandomState(0)
    B, S, H = 8, 512, 1024
    x = paddle.to_tensor(rng.randn(B * S, H).astype("float32"))
    x3 = paddle.to_tensor(rng.randn(B, S, H).astype("float32"))
    w = paddle.to_tensor(rng.randn(H, H).astype("float32"))
    w4 = paddle.to_tensor(rng.randn(H, 4 * H).astype("float32"))
    big = paddle.to_tensor(rng.randn(B, S, 4 * H).astype("float32"))
    qkv = paddle.to_tensor(rng.randn(B, S, 16, 64).astype("float32"))
    logits = paddle.to_tensor(rng.randn(B * S, 32000).astype("float32"))
    labels = paddle.to_tensor(rng.randint(0, 32000, (B * S,)).astype("int64"))
    ids = paddle.to_tensor(rng.randint(0, 32000, (B, S)).astype("int64"))
    img = paddle.to_tensor(rng.randn(8, 64, 56, 56).astype("float32"))
    kern = paddle.to_tensor(rng.randn(64, 64, 3, 3).astype("float32"))
    emb_w = paddle.to_tensor(rng.randn(32000, H).astype("float32"))
    ln = nn.LayerNorm(H)
    rms = nn.RMSNorm(H)
    bn = nn.BatchNorm2D(64)
    bn.eval()
    idx = paddle.to_tensor(rng.randint(0, B * S, (4096,)).astype("int64"))
    b_h = paddle.to_tensor(rng.randn(H).astype("float32"))

    cases = [
        ("matmul", lambda: paddle.matmul(x, w)),
        ("matmul_4h", lambda: paddle.matmul(x3, w4)),
        ("linear_bias", lambda: F.linear(x, w, b_h)),
        ("layer_norm", lambda: ln(x3)),
        ("rms_norm", lambda: rms(x3)),
        ("softmax", lambda: F.softmax(x3, axis=-1)),
        ("sdpa_attention", lambda: F.scaled_dot_product_attention(
            qkv, qkv, qkv, is_causal=True)),
        ("cross_entropy", lambda: F.cross_entropy(logits, labels)),
        ("embedding", lambda: F.embedding(ids, emb_w)),
        ("gelu", lambda: F.gelu(big)),
        ("silu", lambda: F.silu(big)),
        ("relu", lambda: F.relu(big)),
        ("tanh", lambda: paddle.tanh(x3)),
        ("add", lambda: x3 + x3),
        ("mul", lambda: x3 * x3),
        ("add_scalar", lambda: x3 + 1.0),
        ("transpose", lambda: paddle.transpose(x3, [0, 2, 1])),
        ("reshape", lambda: paddle.reshape(x3, [B * S, H])),
        ("concat", lambda: paddle.concat([x3, x3], axis=-1)),
        ("split", lambda: paddle.split(x3, 2, axis=-1)),
        ("reduce_sum", lambda: x3.sum()),
        ("reduce_mean_axis", lambda: x3.mean(axis=-1)),
        ("cumsum", lambda: paddle.cumsum(x3, axis=1)),
        ("argmax", lambda: paddle.argmax(logits, axis=-1)),
        ("topk", lambda: paddle.topk(logits, 8, axis=-1)),
        ("gather", lambda: paddle.gather(x, idx)),
        ("where", lambda: paddle.where(x3 > 0, x3, x3 * 0.1)),
        ("conv2d", lambda: F.conv2d(img, kern, padding=1)),
        ("batch_norm", lambda: bn(img)),
        ("max_pool2d", lambda: F.max_pool2d(img, 2, 2)),
        ("dropout_train", lambda: F.dropout(x3, 0.1, training=True)),
        ("clip", lambda: paddle.clip(x3, -1.0, 1.0)),
    ]
    return cases


def bench(iters: int = 30, warmup: int = 5):
    import jax

    import paddle_tpu  # noqa: F401

    dev = jax.devices()[0]
    cases = build_cases()
    ops = {}
    for name, thunk in cases:
        try:
            for _ in range(warmup):
                out = thunk()
            _block(out)
            ts = []
            for _ in range(iters):
                t0 = time.perf_counter()
                out = thunk()
                _block(out)
                ts.append((time.perf_counter() - t0) * 1e6)
            ops[name] = {"us": round(_median(ts), 2)}
        except Exception as e:  # keep sweeping; record the failure
            ops[name] = {"error": f"{type(e).__name__}: {e}"}
    return {
        "device": str(dev),
        "platform": dev.platform,
        "iters": iters,
        "ops": ops,
    }


def _block(out):
    import jax

    leaves = out if isinstance(out, (list, tuple)) else [out]
    for l in leaves:
        data = getattr(l, "_data", l)
        if hasattr(data, "block_until_ready"):
            jax.block_until_ready(data)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None)
    ap.add_argument("--iters", type=int, default=30)
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (skip the TPU tunnel)")
    args = ap.parse_args()
    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")
    result = bench(iters=args.iters)
    text = json.dumps(result, indent=1)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    sys.stdout.write(text + "\n")


if __name__ == "__main__":
    main()
