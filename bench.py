"""Benchmark: flagship GPT training throughput on one chip.

Prints ONE JSON line (driver contract): the flagship GPT-760M fused train
step. ``--all`` additionally benches the north-star-shaped secondary configs
(BASELINE.md): GPT-125M, ResNet-50 eager (config 1), BERT-base via jit
(config 2) — one JSON line each, flagship line last. Every ``--all`` line
also carries the in-era ideal-GEMM anchor (:func:`gemm_anchor`) so
cross-era tunnel variance can be divided out of round-over-round deltas.
``--fused-mlp`` flips the GPT configs onto the fused MLP-block Pallas
kernels (ops/pallas/fused_mlp) — same metric names, same contract; run
with and without for the kernel A/B.

Methodology: the full fused train step (forward + backward + momentum-SGD
update, bf16 weights / fp32 loss) compiled once; K steps chained in a single
device dispatch via ``lax.scan`` so host<->device round-trips (the axon tunnel
adds ~70ms RTT per dispatch) don't pollute the measurement; one device->host
sync at the end. tokens/sec = K * batch * seq / elapsed. The reference
publishes no absolute numbers (BASELINE.md), so vs_baseline reports measured
MFU vs chip peak — the honest utilization signal.

GPT-760M (h=1536, 24L, head_dim 128) is the flagship: it is the largest
BASELINE-shaped config that fits one 16 GB chip (with block rematerialization
+ chunked-remat CE), and its MXU-shaped matmuls make the MFU number
comparable to the A100 north star. The 125M config stays as a secondary line
for round-over-round comparability.
"""
from __future__ import annotations

import json
import time

import numpy as np

PEAKS = {"TPU v5 lite": 197e12, "TPU v5e": 197e12, "TPU v5p": 459e12,
         "TPU v4": 275e12, "TPU v6 lite": 918e12}


def _chip_peak(jax, on_tpu):
    kind = jax.devices()[0].device_kind if on_tpu else ""
    matched = next((k for k in PEAKS if k in kind), None) if on_tpu else None
    peak = PEAKS[matched] if matched else (197e12 if on_tpu else 1e12)
    chip = matched or (f"unknown:{kind}" if on_tpu else "cpu")
    return chip, peak


def bench_gpt(label, hidden, layers, heads, batch, seq, K, recompute,
              on_tpu, donate=False, flash=True, save_attn=True,
              fused_mlp=False):
    import jax
    import jax.numpy as jnp
    from jax import lax

    from paddle_tpu.models import gpt_spmd
    from paddle_tpu.models.gpt import GPTConfig

    if donate is not True and donate is not False and donate != "mom":
        # identity checks: 1 == True under `in`, but the donate_argnums
        # dispatch and the replay branch key on the exact values
        raise ValueError(f"donate must be True/False/'mom', got {donate!r}")
    cfg = GPTConfig(
        vocab_size=50304, hidden_size=hidden, num_layers=layers,
        num_heads=heads, max_seq_len=seq, recompute=recompute,
        use_flash_attention=flash, remat_save_attn=save_attn,
        # --fused-mlp A/B: same metric name, same driver contract — only the
        # block's elementwise implementation flips (fused Pallas kernels vs
        # XLA). Off-TPU the kernels need interpret mode forced.
        fused_mlp=fused_mlp, force_fused_mlp=fused_mlp and not on_tpu,
    )
    if not on_tpu:
        batch, seq, K = 2, 128, 2
    lr, momentum, num_micro = 1e-4, 0.9, 1

    mesh = gpt_spmd.make_mesh(1)
    dtype = jnp.bfloat16 if on_tpu else jnp.float32
    params = gpt_spmd.init_params(cfg, mesh, dtype=dtype)
    mom = jax.tree.map(jnp.zeros_like, params)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)), jnp.int32)
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)), jnp.int32)

    def one_step(p, m, ids_, labels_):
        loss, grads = jax.value_and_grad(gpt_spmd.loss_fn)(
            p, ids_, labels_, cfg, mesh, num_micro
        )
        m2 = jax.tree.map(lambda a, g: momentum * a + g.astype(a.dtype), m, grads)
        p2 = jax.tree.map(lambda a, b: a - lr * b, p, m2)
        return p2, m2, loss

    def many(params, mom, ids, labels):
        def body(carry, _):
            p, m = carry
            p, m, loss = one_step(p, m, ids, labels)
            return (p, m), loss

        (params, mom), losses = lax.scan(body, (params, mom), None, length=K)
        return params, mom, losses

    with jax.set_mesh(mesh):
        # Two axon-tunnel pathologies to avoid in the measurement (each is
        # 4-7x): donated scan-carry buffers (699 vs 121 ms/step), and
        # feeding a jit call's OUTPUT arrays back as the next call's inputs
        # (relayout per execution). The timed call therefore replays the
        # same original input arrays; steady-state per-step cost is the
        # within-scan step either way.
        # donate=True trades the tunnel's donation penalty for HALF the
        # resident state (params+mom single-buffered) — what lets 1.3B fit
        # the 16 GB chip at all; smaller configs skip it (4-7x step cost).
        # donate="mom" single-buffers ONLY the momentum (params stay
        # double-buffered): 3x(p) instead of 4x(p) resident, probing whether
        # the tunnel penalty follows every donated carry or just params.
        donate_idx = {True: (0, 1), "mom": (1,), False: ()}.get(donate, ())
        many_jit = (jax.jit(many, donate_argnums=donate_idx) if donate_idx
                    else jax.jit(many))
        p_cur, m_cur, losses = many_jit(params, mom, ids, labels)  # compile+warmup
        first_losses = np.asarray(losses)  # sync
        if donate is False:
            # the timed run replays the ORIGINAL inputs; holding the warmup
            # outputs (a full params+momentum copy, ~3 GB at 760M) through it
            # is pure waste and is what pushes save_attn over the 16 GB edge
            del p_cur, m_cur
        elif donate == "mom":
            del p_cur  # timed call replays original params; warmup copy dead
        del losses
        t0 = time.perf_counter()
        if donate is True:
            # donated buffers are consumed: the timed call continues from
            # the returned state (the steady-state training pattern)
            p_cur, m_cur, losses = many_jit(p_cur, m_cur, ids, labels)
        elif donate == "mom":
            # params are NOT donated: replay the ORIGINAL params buffer
            # (feeding the warmup call's params output back would add the
            # relayout pathology this mode exists to isolate); momentum WAS
            # consumed, so continue from the returned buffer
            p_cur, m_cur, losses = many_jit(params, m_cur, ids, labels)
        else:
            # replay the ORIGINAL inputs: feeding a jit output back as input
            # relayouts per execution on this tunnel (see note above)
            _, _, losses = many_jit(params, mom, ids, labels)
        _ = np.asarray(losses)  # sync
        elapsed = time.perf_counter() - t0

    tps = K * batch * seq / elapsed
    n_params = cfg.num_params()
    flops_per_token = 6 * n_params + 6 * layers * hidden * seq
    chip, peak = _chip_peak(jax, on_tpu)
    mfu = tps * flops_per_token / peak
    assert np.all(np.isfinite(first_losses)), "non-finite training loss"
    out = {
        "metric": f"{label} fused train step tokens/sec/chip "
                  f"(bs{batch} seq{seq}, {chip})",
        "value": round(tps, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu, 4),
    }
    if fused_mlp:
        out["fused_mlp"] = True
    return out


def gemm_anchor(on_tpu, n=4096, iters=24):
    """In-era normalization anchor: a fixed-shape bf16 matmul chain timed
    the same way as the benches (one compiled dispatch, lax.scan inside,
    one sync). Emitted alongside every ``--all`` config's JSON so the
    ±8% cross-era tunnel variance (VERDICT Weak #3) can be divided out:
    a config move that tracks the anchor's move is era noise, not a
    regression. Fixed probe = fixed FLOPs; ``anchor_frac_peak`` is the
    era's achievable fraction of chip peak on ideal GEMM content."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    if not on_tpu:
        n, iters = 256, 2
    dtype = jnp.bfloat16 if on_tpu else jnp.float32
    rng = np.random.RandomState(0)
    a = jnp.asarray(rng.randn(n, n) * 0.02, dtype)
    b = jnp.asarray(rng.randn(n, n) * 0.02, dtype)

    def chain(a, b):
        # data-dependent chain: no two matmuls can run concurrently and
        # none can be DCE'd; 0.02 scale keeps bf16 values finite
        def body(c, _):
            return a @ c, None

        c, _ = lax.scan(body, b, None, length=iters)
        return c

    with jax.default_matmul_precision("default"):
        f = jax.jit(chain)
        f(a, b).block_until_ready()  # compile + warmup
        t0 = time.perf_counter()
        f(a, b).block_until_ready()
        elapsed = time.perf_counter() - t0
    flops = 2 * n ** 3 * iters
    chip, peak = _chip_peak(jax, on_tpu)
    return {
        "anchor_gemm": f"{n}x{n}x{n}x{iters} {jnp.dtype(dtype).name} ({chip})",
        "anchor_tflops": round(flops / elapsed / 1e12, 2),
        "anchor_frac_peak": round(flops / elapsed / peak, 4),
    }


def bench_resnet_eager(on_tpu):
    """BASELINE config 1: ResNet-50 dygraph on CIFAR-10-shaped data.

    True eager: one framework-op dispatch per layer, backward on the tape,
    optimizer step — no jit of the step. FLAGS_eager_op_cache is on (the
    framework's cached per-op executables — reference parity: cached kernel
    selection + pregenerated ad_funcs), worth 15.7x through this tunnel
    (4.7 -> 73.9 img/s) because each composite op costs ONE dispatch."""
    import paddle_tpu as paddle
    from paddle_tpu.framework import flags as _flags
    from paddle_tpu.vision.models import resnet50

    _prev_cache = _flags.flag("eager_op_cache")
    _flags.set_flags({"eager_op_cache": True})

    batch = 64 if on_tpu else 8
    K = 5 if on_tpu else 2
    m = resnet50(num_classes=10)
    opt = paddle.optimizer.Momentum(learning_rate=0.01,
                                    parameters=m.parameters())
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(batch, 3, 32, 32).astype("float32"))
    y = paddle.to_tensor(rng.randint(0, 10, (batch,)), dtype="int64")

    def step():
        loss = paddle.nn.functional.cross_entropy(m(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    try:
        loss = step()  # warmup (lazy compiles inside eager ops)
        _ = float(loss.numpy())
        t0 = time.perf_counter()
        for _ in range(K):
            loss = step()
        _ = float(loss.numpy())
        elapsed = time.perf_counter() - t0
    finally:
        _flags.set_flags({"eager_op_cache": _prev_cache})
    return {
        "metric": f"resnet50 eager train step images/sec (bs{batch}, "
                  "CIFAR-10 shapes)",
        "value": round(K * batch / elapsed, 1),
        "unit": "images/s",
        "vs_baseline": 0.0,
    }


def bench_resnet_jit(on_tpu):
    """ResNet-50 train step jit-compiled (what eager mode costs vs compiled
    on this tunnel — the eager number measures dispatch RTT, this one the
    chip)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    import paddle_tpu as paddle
    from paddle_tpu.autograd import no_grad
    from paddle_tpu.jit.api import _named_state, functional_call
    from paddle_tpu.vision.models import resnet50

    batch = 256 if on_tpu else 8
    K = 10 if on_tpu else 2
    paddle.seed(0)
    m = resnet50(num_classes=10)
    # train-mode BN: running-stat updates are captured as functional state
    # (functional_call return_state) and ride the scan carry — full
    # reference train-step semantics, no eval-BN shortcut
    m.train()
    state = {n: t._data for n, t in _named_state(m).items()}
    buf_names = {n for n, _ in m.named_buffers()}
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(batch, 3, 32, 32), jnp.float32)
    y = jnp.asarray(rng.randint(0, 10, (batch,)), jnp.int32)

    def loss_fn(params, x, y):
        with no_grad():
            logits, new_state = functional_call(
                m, params, paddle.Tensor(x), return_state=True)
            loss = paddle.nn.functional.cross_entropy(
                logits, paddle.Tensor(y))
        bufs = {k: v._data if hasattr(v, "_data") else v
                for k, v in new_state.items() if k in buf_names}
        return loss._data.astype(jnp.float32), bufs

    trainable = {k for k, v in state.items()
                 if jnp.issubdtype(v.dtype, jnp.floating)
                 and k not in buf_names}
    p_f = {k: v for k, v in state.items() if k in trainable}
    p_i = {k: v for k, v in state.items() if k not in trainable}

    def many(p_f, bufs, x, y):
        def body(carry, _):
            p, bf = carry
            (loss, bf2), g = jax.value_and_grad(
                lambda pf: loss_fn({**pf, **p_i, **bf}, x, y),
                has_aux=True)(p)
            p = jax.tree.map(lambda a, b: a - 1e-8 * b, p, g)  # tiny lr: keeps the scan carry live (no loop-invariant hoisting) without divergence
            return (p, bf2), loss

        return lax.scan(body, (p_f, bufs), None, length=K)

    bufs0 = {k: v for k, v in state.items() if k in buf_names}
    f = jax.jit(many)
    _, losses = f(p_f, bufs0, x, y)
    first = np.asarray(losses)
    t0 = time.perf_counter()
    _, losses = f(p_f, bufs0, x, y)
    _ = np.asarray(losses)
    elapsed = time.perf_counter() - t0
    assert np.all(np.isfinite(first)), "non-finite resnet loss"
    return {
        "metric": f"resnet50 jit train step images/sec (bs{batch}, "
                  "CIFAR-10 shapes)",
        "value": round(K * batch / elapsed, 1),
        "unit": "images/s",
        "vs_baseline": 0.0,
    }


def bench_bert_jit(on_tpu):
    """BASELINE config 2: BERT-base pretraining step via jit compile."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    import paddle_tpu as paddle
    from paddle_tpu.jit.api import _named_state, functional_call
    from paddle_tpu.models import BertForPretraining
    from paddle_tpu.models.bert import BertConfig

    batch, seq = (128, 128) if on_tpu else (2, 32)
    K = 10 if on_tpu else 2
    cfg = BertConfig(hidden_dropout=0.0, attn_dropout=0.0)  # bert-base
    paddle.seed(0)
    m = BertForPretraining(cfg)
    dtype = jnp.bfloat16 if on_tpu else jnp.float32
    params = {n: t._data.astype(dtype) if jnp.issubdtype(t._data.dtype, jnp.floating)
              else t._data
              for n, t in _named_state(m).items()}
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)), jnp.int64)
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)), jnp.int64)
    nsp = jnp.asarray(rng.randint(0, 2, (batch,)), jnp.int64)

    def loss_fn(params, ids, labels, nsp):
        # no_grad: outer value_and_grad differentiates through the jax graph
        # (incl. the flash kernel's custom_vjp); the framework tape would
        # build a redundant inner jax.vjp around each op — wasted tracing and
        # a Mosaic lowering bug with nested custom-vjp on this toolchain.
        from paddle_tpu.autograd import no_grad

        with no_grad():
            out = functional_call(
                m, params, paddle.Tensor(ids),
                masked_lm_labels=paddle.Tensor(labels),
                next_sentence_label=paddle.Tensor(nsp))
        return out._data.astype(jnp.float32)

    def one_step(p, mom, ids, labels, nsp):
        loss, grads = jax.value_and_grad(loss_fn)(p, ids, labels, nsp)
        mom2 = jax.tree.map(lambda a, g: 0.9 * a + g.astype(a.dtype), mom, grads)
        p2 = jax.tree.map(lambda a, b: a - 1e-4 * b, p, mom2)
        return p2, mom2, loss

    def many(p, mom, ids, labels, nsp):
        def body(carry, _):
            p, mom = carry
            p, mom, loss = one_step(p, mom, ids, labels, nsp)
            return (p, mom), loss

        (p, mom), losses = lax.scan(body, (p, mom), None, length=K)
        return p, mom, losses

    mom = jax.tree.map(
        lambda a: jnp.zeros_like(a) if jnp.issubdtype(a.dtype, jnp.floating)
        else None, params)
    mom = {k: v for k, v in mom.items() if v is not None}
    params_f = {k: v for k, v in params.items() if k in mom}
    params_i = {k: v for k, v in params.items() if k not in mom}

    def many_wrap(p_f, mom, ids, labels, nsp):
        return many({**p_f, **params_i}, mom, ids, labels, nsp)

    f = jax.jit(many_wrap)
    _, _, losses = f(params_f, mom, ids, labels, nsp)
    first = np.asarray(losses)
    t0 = time.perf_counter()
    _, _, losses = f(params_f, mom, ids, labels, nsp)
    _ = np.asarray(losses)
    elapsed = time.perf_counter() - t0
    tps = K * batch * seq / elapsed
    n_params = sum(int(np.prod(v.shape)) for v in params_f.values())
    flops_per_token = 6 * n_params + 12 * cfg.num_layers * cfg.hidden_size * seq
    chip, peak = _chip_peak(jax, on_tpu)
    assert np.all(np.isfinite(first)), "non-finite BERT loss"
    return {
        "metric": f"bert-base jit pretraining tokens/sec/chip "
                  f"(bs{batch} seq{seq}, {chip})",
        "value": round(tps, 1),
        "unit": "tokens/s",
        "vs_baseline": round(tps * flops_per_token / peak, 4),
    }


def bench_dp_quant(on_tpu):
    """Round-14 dp=2 gradient-sync A/B: implicit GSPMD fp allreduce vs the
    int8 quantized ring (``distributed.compressed_collectives`` behind
    ``build_spmd_train_step(comm_quant="int8")``).

    One JSON line: the int8 leg's throughput (``vs_baseline`` = speedup
    over the fp leg — ~1.0 on the CPU smoke where the virtual-device
    "wire" is memcpy; the wire-byte model is what the metric carries),
    ``bytes_on_the_wire``/``bytes_on_the_wire_fp``/``wire_reduction`` from
    the analytic per-replica ring model, ``loss_parity_delta`` (max
    relative deviation of the int8 loss trajectory vs the fp oracle over
    the benched steps — both runs deterministic, same init/data), and
    ``replicas_bit_identical`` (params after the int8 steps byte-equal
    across the dp replicas' shards). Needs >= 2 devices (main() forces 2
    virtual host devices off-TPU, like bench_serve's spmd leg)."""
    import jax
    import jax.numpy as jnp

    # round 23: the wire model rides the shared analysis constants module
    # (same import the JX009 HLO contract reads) — one source of truth
    # for the analytic bytes this line carries
    from paddle_tpu.analysis.cost_model import bytes_on_the_wire
    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.models.gpt_spmd import build_spmd_train_step
    from jax.sharding import Mesh

    from paddle_tpu.observability import default_registry

    if len(jax.devices()) < 2:
        raise RuntimeError("dp-quant A/B needs >= 2 devices")
    if on_tpu:
        hidden, layers, heads, batch, seq, steps = 768, 12, 12, 8, 1024, 8
    else:
        hidden, layers, heads, batch, seq, steps = 64, 2, 4, 8, 64, 6
    cfg = GPTConfig(vocab_size=256, hidden_size=hidden, num_layers=layers,
                    num_heads=heads, max_seq_len=seq)
    mesh = Mesh(np.array(jax.devices()[:2]).reshape(2, 1, 1),
                ("dp", "pp", "mp"))

    def run(comm_quant):
        step, params, mom, (ids, labels) = build_spmd_train_step(
            cfg, mesh, batch_size=batch, seq_len=seq, comm_quant=comm_quant)
        # warmup = step 1 of the deterministic trajectory (params/mom are
        # donated, so training continues from the returned state); only
        # the post-compile steps are timed
        params, mom, loss = step(params, mom, ids, labels)
        losses = [float(loss)]
        t0 = time.perf_counter()
        for _ in range(steps - 1):
            params, mom, loss = step(params, mom, ids, labels)
            losses.append(float(loss))
        elapsed = time.perf_counter() - t0
        return losses, params, (steps - 1) * batch * seq / elapsed

    # round 15: the library-wide metrics registry records both legs'
    # train-step counters + the analytic wire bytes actually charged per
    # step (labeled fp vs int8) — the snapshot rides the emitted line
    default_registry.reset()
    default_registry.enable()
    try:
        fp_losses, _, fp_tps = run(None)
        q_losses, q_params, q_tps = run("int8")
    finally:
        default_registry.disable()
    telemetry = default_registry.snapshot_flat()
    parity = max(abs(a - b) / max(abs(a), 1e-9)
                 for a, b in zip(fp_losses, q_losses))
    bit_identical = 1.0
    for leaf in jax.tree.leaves(q_params):
        shards = [np.asarray(s.data) for s in leaf.addressable_shards]
        full = [s for s in shards if s.shape == leaf.shape]
        if any(not np.array_equal(full[0], s) for s in full[1:]):
            bit_identical = 0.0
    n_elems = sum(int(np.prod(l.shape)) for l in jax.tree.leaves(q_params))
    elem_bytes = jnp.dtype(jax.tree.leaves(q_params)[0].dtype).itemsize
    wire_fp = bytes_on_the_wire(n_elems, 2, elem_bytes=elem_bytes)
    wire_q = bytes_on_the_wire(n_elems, 2, elem_bytes=elem_bytes,
                               quant="int8")
    chip, _ = _chip_peak(jax, on_tpu)
    return {
        "metric": f"gpt dp2 int8-quantized gradient allreduce train step "
                  f"tokens/sec/chip (bs{batch} seq{seq}, {chip})",
        "value": round(q_tps, 1),
        "unit": "tokens/s",
        "vs_baseline": round(q_tps / fp_tps, 4),
        "comm_quant": "int8",
        "bytes_on_the_wire": wire_q,
        "bytes_on_the_wire_fp": wire_fp,
        "wire_reduction": round(wire_fp / wire_q, 4),
        "loss_parity_delta": parity,
        "replicas_bit_identical": bit_identical,
        "telemetry": telemetry,
    }


FLAGSHIP_METRIC = "gpt3-760m(+remat) fused train step tokens/sec/chip"


def _error_line(msg, metric=FLAGSHIP_METRIC):
    """Driver-contract JSON line for a failed run (value 0, error recorded)."""
    return json.dumps({
        "metric": metric, "value": 0, "unit": "tokens/s",
        "vs_baseline": 0.0, "error": msg[:300],
    })


def _run_shielded(timeout=1500):
    """Re-exec the bench in a killable child; emit error JSON if it dies.

    When the TPU tunnel is down, ``jax.devices()`` (and any dispatch) HANGS
    rather than raising — round 4 lost its entire bench evidence to exactly
    this (rc=1 traceback / rc=124 driver timeout, no JSON). A short-timeout
    probe child fails fast on a dead tunnel (~2 min, far under any driver
    budget); the full bench then runs in its own killable child so mid-run
    hangs also become one structured line. The parent never touches jax.
    """
    import os
    import subprocess
    import sys

    timeout = float(os.environ.get("_BENCH_SHIELD_TIMEOUT", timeout))
    probe_timeout = float(os.environ.get("_BENCH_PROBE_TIMEOUT", 180))
    try:
        subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            timeout=probe_timeout, check=True,
        )
    except subprocess.TimeoutExpired:
        print(_error_line("backend_unavailable: device probe timed out "
                          "(tunnel hang)"))
        return
    except subprocess.CalledProcessError as e:
        print(_error_line(f"backend_unavailable: device probe rc={e.returncode}"))
        return

    # -u: line-buffer the child through the pipe so a later kill can't
    # swallow already-printed JSON lines
    env = dict(os.environ, _BENCH_CHILD="1")
    try:
        proc = subprocess.run(
            [sys.executable, "-u", os.path.abspath(__file__), *sys.argv[1:]],
            stdout=subprocess.PIPE, stderr=sys.stderr, text=True,
            timeout=timeout, env=env,
        )
        out, rc = proc.stdout, proc.returncode
    except subprocess.TimeoutExpired as e:
        out = e.output or ""
        out = out if isinstance(out, str) else out.decode(errors="replace")
        rc = None
    if out:
        sys.stdout.write(out if out.endswith("\n") else out + "\n")
    if rc != 0:
        why = ("backend_unavailable: bench child timed out (tunnel hang)"
               if rc is None else f"bench child failed rc={rc}")
        print(_error_line(why))


def main():
    import os
    import sys

    if "--dpquant" in sys.argv:
        # the dp=2 A/B needs two devices: force virtual host devices
        # BEFORE the backend initializes (CPU backend only — a real TPU
        # pod ignores the host-platform flag), like bench_serve --smoke
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=2")
    if "--cpu" in sys.argv:
        # sitecustomize force-sets jax_platforms="axon,cpu"; config overrides it
        import jax as _j

        _j.config.update("jax_platforms", "cpu")
    elif not os.environ.get("_BENCH_CHILD"):
        return _run_shielded()
    import paddle_tpu  # noqa: F401  framework config (x64, matmul precision)
    import jax

    # Benchmark path: 32-bit index types (x64 costs ~25% on this step)
    jax.config.update("jax_enable_x64", False)

    on_tpu = jax.devices()[0].platform == "tpu"
    fused_mlp = "--fused-mlp" in sys.argv

    if "--dpquant" in sys.argv:
        # round-14 standalone mode (the tier-1 gate in
        # tests/test_distributed.py drives it): ONE schema-checked line
        from paddle_tpu.analysis.bench_schema import checked_line

        metric = "gpt dp2 int8-quantized gradient allreduce tokens/sec/chip"
        try:
            print(checked_line(bench_dp_quant(on_tpu)))
        except Exception as e:
            print(_error_line(f"{type(e).__name__}: {e}", metric=metric))
        return

    # In-era anchor: measured ONCE per --all run, merged into every line so
    # each config's JSON carries the era's ideal-GEMM throughput next to it.
    anchor = None
    if "--all" in sys.argv or "--anchor" in sys.argv:
        try:
            anchor = gemm_anchor(on_tpu)
        except Exception as e:
            anchor = {"anchor_error": f"{type(e).__name__}: {e}"[:120]}

    def emit(d):
        # schema-checked emit (tpulint BL001 contract): a malformed line
        # fails HERE, not two rounds later as a silently skewed delta
        from paddle_tpu.analysis.bench_schema import checked_line

        print(checked_line({**d, **anchor} if anchor else d))

    if "--all" in sys.argv:
        emit(bench_gpt("gpt3-125m", 768, 12, 12, 8, 1024, 20,
                       False, on_tpu, fused_mlp=fused_mlp))
        emit(bench_resnet_eager(on_tpu))
        emit(bench_resnet_jit(on_tpu))
        emit(bench_bert_jit(on_tpu))
        try:
            # BASELINE config 3 (single-chip line): donation halves resident
            # state so 1.3B + momentum fits 16 GB; ZeRO/DP scaling of this
            # config is exercised on the virtual mesh (dryrun_multichip)
            # save_attn=False: the memory-edge config keeps its proven-fit
            # footprint (the attention re-forward costs less than an OOM)
            emit(bench_gpt("gpt3-1.3b(+remat,donated)", 2048, 24,
                           16, 4, 1024, 5, True, on_tpu,
                           donate=True, save_attn=False,
                           fused_mlp=fused_mlp))
        except Exception as e:  # OOM must not kill the flagship line below
            print(_error_line(f"{type(e).__name__}: {e}",
                              metric="gpt3-1.3b tokens/sec/chip"))
    one = next((a for a in sys.argv if a.startswith("--exp13b-one=")), None)
    if one:
        mode = {"False": False, "mom": "mom", "True": True}[one.split("=")[1]]
        # save_attn=False: the memory-edge config keeps the proven-fit
        # footprint (with save_attn on, ALL modes OOM — measured r5)
        try:
            r = bench_gpt(f"gpt3-1.3b(donate={mode})", 2048, 24, 16, 4,
                          1024, 5, True, on_tpu, donate=mode,
                          save_attn=False)
        except Exception as e:
            r = json.loads(_error_line(f"{type(e).__name__}: {e}",
                                       metric=f"gpt3-1.3b(donate={mode})"))
        print(json.dumps(r))
        return
    if "--exp13b" in sys.argv:
        # BASELINE config-3 de-noising experiments (round-4 verdict #6):
        # which buffers must be donated for 1.3B to fit, and what each
        # donation mode costs through the tunnel. One SUBPROCESS per mode:
        # an OOM'd attempt leaves the chip unable to fit the next mode in
        # the same process (measured r5 — donate=True alone fits, but fails
        # after a donate=False OOM), so isolation is part of the method.
        import subprocess

        for mode in ("False", "mom", "True"):
            try:
                proc = subprocess.run(
                    [sys.executable, "-u", os.path.abspath(__file__),
                     f"--exp13b-one={mode}"],
                    stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                    text=True, timeout=900,
                    env=dict(os.environ, _BENCH_CHILD="1"),
                )
            except subprocess.TimeoutExpired:
                # one hung mode (dead tunnel mid-sweep) must not abort the
                # remaining modes — mirror _run_shielded's structured line
                print(_error_line(
                    "backend_unavailable: exp13b child timed out "
                    "(tunnel hang)", metric=f"gpt3-1.3b(donate={mode})"))
                continue
            out = proc.stdout.strip()
            print(out if out else _error_line(
                f"exp13b child rc={proc.returncode}",
                metric=f"gpt3-1.3b(donate={mode})"))
        return

    # flagship line LAST (the driver reads one line; keep it the final one).
    # save_attn=True is the round-4 default (backward skips the attention
    # re-forward for ~0.6 GB extra residency); if a memory regression ever
    # trips it, fall back to the proven-fit policy rather than losing the
    # flagship line.
    out = err = None
    try:
        out = bench_gpt("gpt3-760m(+remat)", 1536, 24, 12, 8, 1024,
                        10, True, on_tpu, fused_mlp=fused_mlp)
    except Exception as e:
        err = f"{type(e).__name__}: {e}"[:200]
        # drop the traceback's frame refs NOW: while a handler runs, the
        # in-flight exception (sys.exc_info) pins bench_gpt's device buffers,
        # so the fallback must run OUTSIDE the except block, after collection
        e.__traceback__ = None
    if out is None:
        import gc

        gc.collect()
        out = bench_gpt("gpt3-760m(+remat,reforward)", 1536, 24, 12, 8,
                        1024, 10, True, on_tpu, save_attn=False,
                        fused_mlp=fused_mlp)
        out["save_attn_error"] = err
    emit(out)


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # last line must stay parseable for the driver
        import sys
        import traceback

        traceback.print_exc(file=sys.stderr)
        print(_error_line(f"{type(e).__name__}: {e}"))
        # exit 0: the driver contract is "parseable JSON, rc 0"; the shielded
        # parent passes this line through without adding a duplicate
