"""Benchmark: flagship GPT training throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Methodology: the full fused train step (forward + backward + momentum-SGD
update, bf16 weights / fp32 loss) compiled once; K steps chained in a single
device dispatch via ``lax.scan`` so host<->device round-trips (the axon tunnel
adds ~70ms RTT per dispatch) don't pollute the measurement; one device->host
sync at the end. tokens/sec = K * batch * seq / elapsed. The reference
publishes no absolute numbers (BASELINE.md), so vs_baseline reports measured
MFU vs chip peak — the honest utilization signal.
"""
from __future__ import annotations

import json
import time

import numpy as np


def main():
    import sys

    if "--cpu" in sys.argv:
        # sitecustomize force-sets jax_platforms="axon,cpu"; config overrides it
        import jax as _j

        _j.config.update("jax_platforms", "cpu")
    import paddle_tpu  # noqa: F401  framework config (x64, matmul precision)
    import jax

    # Benchmark path: 32-bit index types (x64 costs ~25% on this step)
    jax.config.update("jax_enable_x64", False)
    import jax.numpy as jnp
    from jax import lax

    from paddle_tpu.models import gpt_spmd
    from paddle_tpu.models.gpt import GPTConfig

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"

    cfg = GPTConfig(
        vocab_size=50304, hidden_size=768, num_layers=12, num_heads=12,
        max_seq_len=1024,
    )  # gpt3-125m
    batch, seq = (8, 1024) if on_tpu else (2, 128)
    K = 20 if on_tpu else 2
    lr, momentum, num_micro = 1e-4, 0.9, 1

    mesh = gpt_spmd.make_mesh(1)
    dtype = jnp.bfloat16 if on_tpu else jnp.float32
    params = gpt_spmd.init_params(cfg, mesh, dtype=dtype)
    mom = jax.tree.map(jnp.zeros_like, params)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)), jnp.int32)
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)), jnp.int32)

    def one_step(p, m, ids_, labels_):
        loss, grads = jax.value_and_grad(gpt_spmd.loss_fn)(
            p, ids_, labels_, cfg, mesh, num_micro
        )
        m2 = jax.tree.map(lambda a, g: momentum * a + g.astype(a.dtype), m, grads)
        p2 = jax.tree.map(lambda a, b: a - lr * b, p, m2)
        return p2, m2, loss

    def many(params, mom, ids, labels):
        def body(carry, _):
            p, m = carry
            p, m, loss = one_step(p, m, ids, labels)
            return (p, m), loss

        (params, mom), losses = lax.scan(body, (params, mom), None, length=K)
        return params, mom, losses

    with jax.set_mesh(mesh):
        # Two axon-tunnel pathologies to avoid in the measurement (each is
        # 4-7x): donated scan-carry buffers (699 vs 121 ms/step), and
        # feeding a jit call's OUTPUT arrays back as the next call's inputs
        # (relayout per execution). The timed call therefore replays the
        # same original input arrays; steady-state per-step cost is the
        # within-scan step either way.
        many_jit = jax.jit(many)
        _, _, losses = many_jit(params, mom, ids, labels)  # compile+warmup
        first_losses = np.asarray(losses)  # sync
        t0 = time.perf_counter()
        _, _, losses = many_jit(params, mom, ids, labels)
        _ = np.asarray(losses)  # sync
        elapsed = time.perf_counter() - t0

    tokens = K * batch * seq
    tps = tokens / elapsed

    n_params = cfg.num_params()
    l, h, s = cfg.num_layers, cfg.hidden_size, seq
    flops_per_token = 6 * n_params + 6 * l * h * s  # matmuls + causal attention
    kind = jax.devices()[0].device_kind if on_tpu else ""
    # bf16 peak by chip generation (MFU denominator must match the chip the
    # driver actually provides — this tunnel exposes a v5e)
    peaks = {"TPU v5 lite": 197e12, "TPU v5e": 197e12, "TPU v5p": 459e12,
             "TPU v4": 275e12, "TPU v6 lite": 918e12}
    matched = next((k for k in peaks if k in kind), None) if on_tpu else None
    peak = peaks[matched] if matched else (197e12 if on_tpu else 1e12)
    # surface the denominator in the metric so an unmatched device_kind
    # (silent v5e fallback) is auditable from the output alone
    chip = matched or (f"unknown:{kind}" if on_tpu else "cpu")
    mfu = tps * flops_per_token / peak

    assert np.all(np.isfinite(first_losses)), "non-finite training loss"
    print(
        json.dumps(
            {
                "metric": f"gpt3-125m fused train step tokens/sec/chip (bs{batch} seq{seq}, {chip})",
                "value": round(tps, 1),
                "unit": "tokens/s",
                "vs_baseline": round(mfu, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
